"""Ring attention + sequence-parallel mapping tests (long-context layer;
beyond-reference capability — the reference has no CP/SP at all)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.flash_attention import mha_reference
from apex_tpu.transformer.context_parallel import (
    gather_from_sequence_parallel_region, reduce_scatter_to_sequence_parallel_region,
    ring_attention, scatter_to_sequence_parallel_region)

CP = 4


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:CP]), ("context",))


def _qkv(b=2, h=2, s=64, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(mesh, causal):
    q, k, v = _qkv(seed=1)

    def run(q, k, v):
        def inner(q, k, v):
            return ring_attention(q, k, v, "context", causal=causal)
        spec = P(None, None, "context", None)
        return shard_map(inner, mesh=mesh, in_specs=(spec,) * 3,
                         out_specs=spec)(q, k, v)

    out = jax.jit(run)(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("remat", [True, False])
def test_ring_attention_grads_match_reference(mesh, remat):
    q, k, v = _qkv(seed=2)
    dy = jnp.asarray(np.random.RandomState(3).randn(*q.shape), jnp.float32)

    def ring_loss(q, k, v):
        def inner(q, k, v):
            out = ring_attention(q, k, v, "context", causal=True,
                                 remat=remat)
            return jax.lax.psum(jnp.sum(out * _shard(dy)), "context")

        def _shard(x):
            from apex_tpu.utils.compat import axis_size
            cp = axis_size("context")
            r = jax.lax.axis_index("context")
            chunk = x.shape[2] // cp
            return jax.lax.dynamic_slice_in_dim(x, r * chunk, chunk, 2)

        spec = P(None, None, "context", None)
        return shard_map(inner, mesh=mesh, in_specs=(spec,) * 3,
                         out_specs=P())(q, k, v)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) * dy),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_ring_attention_bf16_and_uneven_rank_content(mesh):
    """bf16 inputs, fp32 accumulation; content differs per rank so any
    rotation-order bug shows up."""
    q, k, v = _qkv(b=1, h=1, s=128, d=8, seed=4)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def run(q, k, v):
        spec = P(None, None, "context", None)
        return shard_map(
            lambda q, k, v: ring_attention(q, k, v, "context", causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)(q, k, v)

    out = jax.jit(run)(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=3e-2, atol=3e-2)


def test_sequence_parallel_mappings_roundtrip(mesh):
    """scatter -> gather is the identity; reduce_scatter + gather == psum
    (the Megatron-LM SP identities), with ``context`` standing in for the
    tensor axis."""
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(16, 3, 8), jnp.float32)

    def roundtrip(x):
        def inner(x):
            s = scatter_to_sequence_parallel_region(x, "context")
            g = gather_from_sequence_parallel_region(s, "context")
            return jax.lax.pmean(g, "context")
        return shard_map(inner, mesh=mesh, in_specs=P(), out_specs=P())(x)

    np.testing.assert_allclose(np.asarray(jax.jit(roundtrip)(x)),
                               np.asarray(x), rtol=1e-6)

    def rs_then_gather(x):
        def inner(x):
            part = reduce_scatter_to_sequence_parallel_region(x, "context")
            return gather_from_sequence_parallel_region(part, "context")
        return shard_map(inner, mesh=mesh, in_specs=P("context"),
                         out_specs=P("context"))(x)

    # feeding per-rank copies xi: reduce_scatter sums them; gather
    # reassembles the summed sequence
    stacked = jnp.asarray(rng.randn(CP, 16, 3, 8), jnp.float32)
    out = jax.jit(rs_then_gather)(stacked.reshape(CP * 16, 3, 8))
    expect = np.sum(np.asarray(stacked), axis=0)
    np.testing.assert_allclose(
        np.asarray(out).reshape(CP, 16, 3, 8)[0], expect, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(mesh, causal):
    from apex_tpu.transformer.context_parallel import ulysses_attention

    q, k, v = _qkv(b=2, h=4, s=64, d=16, seed=6)

    def run(q, k, v):
        def inner(q, k, v):
            return ulysses_attention(q, k, v, "context", causal=causal)
        spec = P(None, None, "context", None)
        return shard_map(inner, mesh=mesh, in_specs=(spec,) * 3,
                         out_specs=spec)(q, k, v)

    out = jax.jit(run)(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_attention_grads_and_validation(mesh):
    from apex_tpu.transformer.context_parallel import ulysses_attention

    q, k, v = _qkv(b=1, h=4, s=64, d=8, seed=7)
    dy_full = jnp.asarray(np.random.RandomState(8).randn(*q.shape),
                          jnp.float32)

    def loss(q, k, v):
        def inner(q, k, v, dy):
            out = ulysses_attention(q, k, v, "context", causal=True)
            return jax.lax.psum(jnp.sum(out * dy), "context")
        spec = P(None, None, "context", None)
        return shard_map(inner, mesh=mesh, in_specs=(spec,) * 4,
                         out_specs=P())(q, k, v, dy_full)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True)
                                * dy_full), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    # heads must divide cp
    q3, k3, v3 = _qkv(b=1, h=3, s=64, d=8, seed=9)
    with pytest.raises(ValueError):
        spec = P(None, None, "context", None)
        shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, "context"),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)(q3, k3, v3)


def test_context_axis_in_parallel_state():
    """context_parallel_size carves a first-class mesh axis; ring attention
    runs over it inside the hybrid mesh, and the flat-rank group
    enumerations account for the new dimension."""
    from apex_tpu.transformer import parallel_state

    m = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, context_parallel_size=2)
    try:
        assert parallel_state.get_context_parallel_world_size() == 2
        assert parallel_state.get_data_parallel_world_size() == 2
        # layout: tp fastest, then cp, then dp
        assert parallel_state.get_tensor_model_parallel_groups()[:2] == [
            [0, 1], [2, 3]]
        assert parallel_state.get_context_parallel_groups()[:2] == [
            [0, 2], [1, 3]]
        assert parallel_state.get_data_parallel_groups()[0] == [0, 4]

        q, k, v = _qkv(b=1, h=2, s=32, d=8, seed=10)

        def run(q, k, v):
            def inner(q, k, v):
                out = ring_attention(q, k, v, "context", causal=True)
                return jax.lax.pmean(jax.lax.pmean(
                    jax.lax.pmean(out, "data"), "tensor"), "pipe")
            spec = P(None, None, "context", None)
            return shard_map(inner, mesh=m, in_specs=(spec,) * 3,
                             out_specs=spec)(q, k, v)

        out = jax.jit(run)(q, k, v)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        parallel_state.destroy_model_parallel()
