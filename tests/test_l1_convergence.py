"""L1-equivalent convergence matrix.

Reference: ``reference:tests/L1/common/run_test.sh:22-50`` sweeps
opt_level {O0..O3} x loss_scale {none, static, dynamic} x
keep_batchnorm_fp32 on real ResNet-50 and ``compare.py:34-40`` diffs the
per-iteration loss digests between runs. Here the same matrix runs on
RN50-tiny and GPT-tiny (with dropout active, exercising the RNG streams)
in minutes on the CPU mesh; each cell asserts

  1. every loss in the trajectory is finite (no silent overflow),
  2. the model converges (final-window mean well below the start),
  3. the trajectory tracks the O0 fp32 reference within a
     dtype-calibrated band (the ``compare.py`` digest role), and
  4. rerunning a cell reproduces its trajectory bit-for-bit (determinism
     digest — dropout included).

A ZeRO cell runs the same GPT trajectory under ``DistributedFusedAdam``
on the 8-device mesh and must match the dense FusedAdam trajectory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.amp import all_finite, get_policy, make_loss_scale
from apex_tpu.models import (GPTConfig, GPTModel, ResNet50, ResNetConfig)
from apex_tpu.optimizers import (DistributedFusedAdam, FusedAdam,
                                 ZeroAdamState)

STEPS = 40
WINDOW = 8

CELLS = [
    # (opt_level, loss_scale override, keep_norms_fp32 override)
    ("O0", None, None),
    ("O1", None, None),
    ("O1", "dynamic", None),
    ("O2", None, None),
    ("O2", 128.0, None),
    ("O2", "dynamic", None),
    ("O2", None, False),
    ("O3", None, None),
    ("O3", 128.0, None),
]


def _policy(opt_level, scale, norms):
    kw = {}
    if scale is not None or opt_level != "O0":
        kw["loss_scale"] = scale
    if norms is not None:
        kw["keep_norms_fp32"] = norms
    pol = get_policy(opt_level, half_dtype=jnp.bfloat16, **kw)
    return pol


def _train(loss_of_params, params, policy, steps=STEPS, lr=5e-3):
    """Generic amp training loop: policy casts, loss scaling, overflow
    skip, FusedAdam."""
    scaler = make_loss_scale(policy.loss_scale)
    ls = scaler.init()
    opt = FusedAdam(lr=lr)
    params = jax.tree_util.tree_map(
        lambda p: p.astype(policy.param_dtype)
        if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
    state = opt.init(params)

    @jax.jit
    def step(params, state, ls, i):
        def scaled(p):
            loss = loss_of_params(p, i)
            return scaler.scale(ls, loss), loss
        grads, loss = jax.grad(scaled, has_aux=True)(params)
        grads = scaler.unscale(ls, grads)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, state = opt.step(grads, state, params, grads_finite=finite)
        return params, state, new_ls, loss

    losses = []
    for i in range(steps):
        params, state, ls, loss = step(params, state, ls, jnp.asarray(i))
        losses.append(float(loss))
    return np.asarray(losses)


# ---------------------------------------------------------------------------
# model fixtures
# ---------------------------------------------------------------------------

def _rn50_cell(policy):
    cfg = ResNetConfig(num_classes=10, stage_sizes=(1, 1, 1, 1), width=8,
                       compute_dtype=policy.compute_dtype,
                       params_dtype=policy.param_dtype)
    model = ResNet50(cfg)
    params, bn0 = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 32, 32, 3), policy.compute_dtype)
    labels = jnp.asarray(rng.randint(0, 10, 8))

    def loss_of(p, i):
        # norms stay fp32 via BN state; keep_norms_fp32=False is exercised
        # by casting BN affine params with the tree cast in _train
        logits, _ = model(p, bn0, x, training=True)
        onehot = jax.nn.one_hot(labels, 10)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(
            logits.astype(jnp.float32)) * onehot, -1))

    return loss_of, params


def _gpt_cell(policy):
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=16,
                    params_dtype=policy.param_dtype,
                    compute_dtype=policy.compute_dtype,
                    hidden_dropout=0.1, attention_dropout=0.1,
                    use_flash=False)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1))
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 64, (4, 16)))

    def loss_of(p, i):
        # per-step dropout stream: deterministic fold-in (RNG tracker
        # semantics), so reruns digest identically
        rng = jax.random.fold_in(jax.random.PRNGKey(7), i)
        return model.loss(p, tokens, tokens, dropout_rng=rng)

    return loss_of, params


_FIXTURES = {"rn50": _rn50_cell, "gpt": _gpt_cell}


@pytest.mark.slow
@pytest.mark.parametrize("model_name", ["rn50", "gpt"])
def test_l1_convergence_matrix(model_name):
    """>= 9 cells per model; every half-precision cell tracks the O0
    reference."""
    make = _FIXTURES[model_name]
    ref_pol = _policy("O0", None, None)
    loss_of, params = make(ref_pol)
    ref = _train(loss_of, params, ref_pol)
    assert np.all(np.isfinite(ref))
    assert ref[-WINDOW:].mean() < ref[0] * 0.9

    for opt_level, scale, norms in CELLS[1:]:
        pol = _policy(opt_level, scale, norms)
        loss_of, params = make(pol)
        traj = _train(loss_of, params, pol)
        cell = f"{model_name}/{opt_level}/ls={scale}/norms={norms}"
        assert np.all(np.isfinite(traj)), cell
        # converges
        assert traj[-WINDOW:].mean() < traj[0] * 0.9, cell
        # tracks the fp32 reference: same start (identical init), and the
        # final window within a bf16-calibrated band
        # O3 stores params in bf16, shifting even the first loss; 10%%
        # still catches gross divergence
        np.testing.assert_allclose(traj[0], ref[0], rtol=1e-1, err_msg=cell)
        assert abs(traj[-WINDOW:].mean() - ref[-WINDOW:].mean()) \
            < 0.35 * abs(ref[0] - ref[-WINDOW:].mean()), cell


@pytest.mark.slow
def test_l1_determinism_digest():
    """``compare.py``'s expected-vs-permuted role: the same cell rerun
    reproduces its loss digest bit-for-bit, dropout included."""
    pol = _policy("O2", "dynamic", None)
    loss_of, params = _gpt_cell(pol)
    a = _train(loss_of, params, pol, steps=12)
    loss_of, params = _gpt_cell(pol)
    b = _train(loss_of, params, pol, steps=12)
    np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_l1_zero_cell_matches_dense():
    """ZeRO column of the matrix: DistributedFusedAdam on the data mesh
    reproduces the dense FusedAdam trajectory."""
    DP = 4
    mesh = Mesh(np.array(jax.devices()[:DP]), ("data",))
    pol = _policy("O0", None, None)
    loss_of, params = _gpt_cell(pol)

    dense = _train(loss_of, params, pol, steps=10)

    opt = DistributedFusedAdam(lr=5e-3)
    state_spec = ZeroAdamState(step=P(), master=P("data"),
                               exp_avg=P("data"), exp_avg_sq=P("data"))
    pspec = jax.tree_util.tree_map(lambda _: P(), params)

    @jax.jit
    def init_fn(params):
        return shard_map(opt.init, mesh=mesh, in_specs=(pspec,),
                         out_specs=state_spec)(params)

    @jax.jit
    def step(params, state, i):
        loss = loss_of(params, i)
        grads = jax.grad(lambda p: loss_of(p, i))(params)

        def inner(params, state, grads):
            return opt.step(grads, state, params)
        gspec = jax.tree_util.tree_map(lambda _: P(), grads)
        params, state = shard_map(
            inner, mesh=mesh, in_specs=(pspec, state_spec, gspec),
            out_specs=(pspec, state_spec))(params, state, grads)
        return params, state, loss

    p, s = params, init_fn(params)
    zero_losses = []
    for i in range(10):
        p, s, loss = step(p, s, jnp.asarray(i))
        zero_losses.append(float(loss))
    np.testing.assert_allclose(np.asarray(zero_losses), dense, rtol=2e-5)
