"""End-to-end bitwise elastic resume on the real GPT hybrid trainer.

The acceptance-criterion proof: a subprocess (``tests/_elastic_child.py``,
its own virtual 2-device CPU mesh) trains the GPT trainer under
:class:`~apex_tpu.elastic.runner.ElasticRunner`, is preempted — once by
an EXTERNAL ``kill -TERM`` delivered by this parent mid-run, once by a
deterministic :class:`~apex_tpu.elastic.faults.FaultPlan` (self-SIGTERM
at step K + a transient save ``OSError`` + a torn checkpoint dir) — is
relaunched, finishes the remaining steps, and must produce a sha256 over
the bitwise content of params, optimizer state, loss-scale scalars, the
completed-step count, and the data cursor EQUAL to an uninterrupted
N+M-step run. The reference digest is computed in-process from the same
module (one source for the recipe), and the two legs split the
``fp32_on_disk`` settings between them so both on-disk layouts are
proven.

As of PR 8 the child trains the COMPOUND ``fastpath`` configuration
(ZeRO-1 with the backward-interleaved per-bucket RS→math→AG apply on a
multi-bucket bucket-major shard layout + selective remat) — the
kill-and-resume contract is proven on the interleaved-apply program,
including the ``bucket_stamp`` layout guard every restore passes
through. The plain trainer's elastic loop stays covered in-process by
``tests/test_elastic.py`` and the dryrun gate's elastic leg.

Children share one persistent XLA compilation cache dir, so only the
first pays the compile.
"""

import os
import signal
import subprocess
import sys
import tempfile

import jax
import pytest

import _elastic_child as child_mod

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_elastic_child.py")
STEPS = 3


@pytest.fixture(scope="module")
def child_env(tmp_path_factory):
    env = dict(os.environ)
    env["JAX_COMPILATION_CACHE_DIR"] = str(
        tmp_path_factory.mktemp("xla_cache"))
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    return env


def _run_child(env, ckpt_dir, *, fault_json=None, kill_on_step=None,
               timeout=300):
    """Launch the child; optionally deliver SIGTERM when its ``STEP k``
    progress line appears. Returns ``(returncode, stdout_lines)``."""
    cmd = [sys.executable, CHILD, "--ckpt-dir", str(ckpt_dir),
           "--steps", str(STEPS)]
    if fault_json is not None:
        cmd += ["--fault-json", fault_json]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    lines = []
    try:
        for line in proc.stdout:
            line = line.strip()
            lines.append(line)
            if (kill_on_step is not None
                    and line == f"STEP {kill_on_step}"):
                proc.send_signal(signal.SIGTERM)
                kill_on_step = None
        rc = proc.wait(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
    return rc, lines


def _digest_of(lines):
    found = [l.split()[1] for l in lines if l.startswith("DIGEST ")]
    return found[-1] if found else None


@pytest.fixture(scope="module")
def ref_digest():
    """Uninterrupted N+M reference, computed in-process on the first two
    of this process's virtual devices from the SAME recipe module the
    children run (no drift possible)."""
    from apex_tpu.elastic import ElasticRunner
    from apex_tpu.transformer import parallel_state

    trainer, it, _ = child_mod.build_trainer_and_data(jax.devices()[:2])
    try:
        with tempfile.TemporaryDirectory() as d:
            res = ElasticRunner(trainer, it, d, save_interval=1,
                                keep_last=3,
                                exit_on_preempt=False).fit(
                                    STEPS, key=jax.random.PRNGKey(0))
        assert not res.preempted
        return child_mod.state_digest(res.state, res.step, it.consumed)
    finally:
        parallel_state.destroy_model_parallel()


def test_external_sigterm_kill_and_resume_bitwise(child_env, ref_digest,
                                                  tmp_path):
    """kill -TERM from outside while saves (slowed, so one is reliably in
    flight) are streaming — the child drains, commits a final checkpoint,
    exits 0; the relaunched child finishes and matches the reference
    digest bitwise. fp32_on_disk=True leg."""
    ckpt_dir = tmp_path / "ckpt"
    slow = '{"slow_save_s": 0.2}'
    rc, lines = _run_child(child_env, ckpt_dir, fault_json=slow,
                           kill_on_step=1)
    assert rc == 0, "\n".join(lines)

    rc2, lines2 = _run_child(child_env, ckpt_dir, fault_json=slow)
    assert rc2 == 0, "\n".join(lines2)
    digest = _digest_of(lines2) or _digest_of(lines)
    assert digest == ref_digest, (lines, lines2)
    if _digest_of(lines) is None:  # the kill interrupted the first run
        assert any(l.startswith("RESTORED ") for l in lines2)


def test_fault_plan_preemption_torn_fallback_resume_bitwise(
        child_env, ref_digest, tmp_path):
    """Deterministic FaultPlan leg, fp32_on_disk=False: self-SIGTERM
    before step 2 runs, a transient OSError on the step-1 save (retried),
    and the preemption-time step-2 checkpoint torn after commit. The
    resumed child must warn, fall back to COMMITTED step 1, rerun steps
    2..N+M, and still match the reference bitwise."""
    ckpt_dir = tmp_path / "ckpt"
    plan = ('{"sigterm_at_step": 2, "save_errors": {"1": 1}, '
            '"tear_after_step": 2}')
    cmd_extra = ["--fp32-on-disk", "0"]

    cmd = [sys.executable, CHILD, "--ckpt-dir", str(ckpt_dir),
           "--steps", str(STEPS), "--fault-json", plan] + cmd_extra
    out = subprocess.run(cmd, env=child_env, capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    # the preemption is deterministic: the first run never finishes
    assert "DIGEST" not in out.stdout

    cmd2 = [sys.executable, CHILD, "--ckpt-dir", str(ckpt_dir),
            "--steps", str(STEPS)] + cmd_extra
    out2 = subprocess.run(cmd2, env=child_env, capture_output=True,
                          text=True, timeout=300)
    assert out2.returncode == 0, out2.stdout + out2.stderr
    # torn step-2 dir skipped loudly; restored from committed step 1
    assert "torn" in (out2.stdout + out2.stderr)
    assert "RESTORED 1" in out2.stdout
    lines2 = out2.stdout.splitlines()
    assert _digest_of(lines2) == ref_digest, out2.stdout
