"""Pipeline-schedule backward memory accounting.

The reference's 1F1B exists to bound in-flight activations
(``reference:apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_without_interleaving.py:155-345``). Our traced-scan
schedule stores per-tick residuals instead (O(M + L) ticks); these tests
pin down that profile with XLA's compiled memory analysis on the CPU
backend and assert the bound ``remat=True`` guarantees: the per-microbatch
residual cost collapses to the scan carry (one activation per chunk),
intra-stage activations being recomputed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving)

PP = 4
D = 128
MB = 4
LAYERS_PER_STAGE = 3


@pytest.fixture
def mesh():
    m = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size=PP)
    yield m
    parallel_state.destroy_model_parallel()


def _stage_fn(p, x, s):
    # 3 "layers" per stage so intra-stage residuals dominate the carry
    for _ in range(LAYERS_PER_STAGE):
        x = jnp.tanh(x @ p["w"])
    return x


def _temp_bytes(mesh, M, remat):
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(PP, D, D) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.randn(M, MB, D), jnp.float32)

    def run(ws):
        def inner(ws):
            loss, grads = forward_backward_pipelining_without_interleaving(
                _stage_fn, micro, {"w": ws[0]},
                loss_fn=lambda y, m: jnp.mean(y ** 2), remat=remat)
            return loss, grads
        return shard_map(inner, mesh=mesh, in_specs=(P("pipe"),),
                         out_specs=(P(), {"w": P("pipe")}))(ws)

    compiled = jax.jit(run).lower(ws).compile()
    return compiled.memory_analysis().temp_size_in_bytes


def test_backward_memory_is_linear_in_microbatches(mesh):
    """Honest bound: residual memory grows ~linearly with M (ticks), unlike
    true 1F1B's O(pp). This is the documented profile, asserted so a future
    schedule rewrite that achieves 1F1B memory shows up as a (good)
    failure."""
    t8 = _temp_bytes(mesh, 8, remat=False)
    t32 = _temp_bytes(mesh, 32, remat=False)
    slope = (t32 - t8) / 24
    assert slope > 0
    # per-tick residual must be at least the carry (one activation/chunk)
    carry_bytes = MB * D * 4
    assert slope >= carry_bytes


def test_remat_bounds_residuals_to_the_carry(mesh):
    """With remat=True each tick's residual is the carry (plus bounded
    bookkeeping), not the per-layer intermediates: the per-microbatch slope
    must drop well below the no-remat slope and stay within a small
    multiple of the carry size."""
    slope_plain = (_temp_bytes(mesh, 32, False) - _temp_bytes(mesh, 8, False)) / 24
    slope_remat = (_temp_bytes(mesh, 32, True) - _temp_bytes(mesh, 8, True)) / 24
    carry_bytes = MB * D * 4
    # intra-stage residuals (3 tanh layers) are recomputed, not stored
    assert slope_remat <= slope_plain / 2
    assert slope_remat <= 4 * carry_bytes
