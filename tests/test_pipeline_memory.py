"""Pipeline-schedule backward memory accounting.

The reference's 1F1B exists to bound in-flight activations at O(pp)
microbatches (``reference:apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_without_interleaving.py:155-345``,
``free_output_tensor`` at ``common.py:198-249``). The default
``memory_efficient=True`` schedule reproduces that bound with a
hand-driven vjp inside the tick scan — asserted here as O(1)-in-M
compiled temp memory. The AD-through-the-scan driver
(``memory_efficient=False``) keeps its documented O(M + L) per-tick
residual profile, with ``remat=True`` collapsing each tick's residual to
the carry; both profiles are pinned with XLA's compiled memory analysis
on the CPU backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving)

PP = 4
D = 128
MB = 4
LAYERS_PER_STAGE = 3


@pytest.fixture
def mesh():
    m = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size=PP)
    yield m
    parallel_state.destroy_model_parallel()


def _stage_fn(p, x, s):
    # 3 "layers" per stage so intra-stage residuals dominate the carry
    for _ in range(LAYERS_PER_STAGE):
        x = jnp.tanh(x @ p["w"])
    return x


def _temp_bytes(mesh, M, remat, memory_efficient):
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(PP, D, D) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.randn(M, MB, D), jnp.float32)

    def run(ws):
        def inner(ws):
            loss, grads = forward_backward_pipelining_without_interleaving(
                _stage_fn, micro, {"w": ws[0]},
                loss_fn=lambda y, m: jnp.mean(y ** 2), remat=remat,
                memory_efficient=memory_efficient)
            return loss, grads
        return shard_map(inner, mesh=mesh, in_specs=(P("pipe"),),
                         out_specs=(P(), {"w": P("pipe")}))(ws)

    compiled = jax.jit(run).lower(ws).compile()
    return compiled.memory_analysis().temp_size_in_bytes


def test_memory_efficient_1f1b_is_O1_in_microbatches(mesh):
    """The default schedule holds O(pp) activations regardless of M — the
    reference 1F1B's whole point. Temp memory must be flat in M (scan
    bookkeeping only; far below one activation per extra microbatch)."""
    t8 = _temp_bytes(mesh, 8, remat=False, memory_efficient=True)
    t32 = _temp_bytes(mesh, 32, remat=False, memory_efficient=True)
    act_bytes = MB * D * 4
    slope = (t32 - t8) / 24
    assert slope < act_bytes / 4, (t8, t32)


def test_memory_efficient_matches_ad_schedule_outputs(mesh):
    """Same loss and grads as the AD-through-the-scan driver (which is
    itself pinned against no-pipelining elsewhere)."""
    rng = np.random.RandomState(1)
    ws = jnp.asarray(rng.randn(PP, D, D) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.randn(8, MB, D), jnp.float32)

    def run(memory_efficient):
        def inner(ws):
            return forward_backward_pipelining_without_interleaving(
                _stage_fn, micro, {"w": ws[0]},
                loss_fn=lambda y, m: jnp.mean(y ** 2),
                memory_efficient=memory_efficient)
        return shard_map(inner, mesh=mesh, in_specs=(P("pipe"),),
                         out_specs=(P(), {"w": P("pipe")}))(ws)

    loss_a, grads_a = run(True)
    loss_b, grads_b = run(False)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads_a["w"]),
                               np.asarray(grads_b["w"]),
                               rtol=1e-5, atol=1e-6)


def test_ad_schedule_backward_memory_is_linear_in_microbatches(mesh):
    """Honest bound for the AD driver: residual memory grows ~linearly with
    M (ticks), unlike the default's O(pp)."""
    t8 = _temp_bytes(mesh, 8, remat=False, memory_efficient=False)
    t32 = _temp_bytes(mesh, 32, remat=False, memory_efficient=False)
    slope = (t32 - t8) / 24
    assert slope > 0
    # per-tick residual must be at least the carry (one activation/chunk)
    carry_bytes = MB * D * 4
    assert slope >= carry_bytes


def test_ad_schedule_remat_bounds_residuals_to_the_carry(mesh):
    """With remat=True each tick's residual is the carry (plus bounded
    bookkeeping), not the per-layer intermediates."""
    slope_plain = (_temp_bytes(mesh, 32, False, False)
                   - _temp_bytes(mesh, 8, False, False)) / 24
    slope_remat = (_temp_bytes(mesh, 32, True, False)
                   - _temp_bytes(mesh, 8, True, False)) / 24
    carry_bytes = MB * D * 4
    # intra-stage residuals (3 tanh layers) are recomputed, not stored
    assert slope_remat <= slope_plain / 2
    assert slope_remat <= 4 * carry_bytes


def test_memory_efficient_matches_ad_schedule_shared_params(mesh):
    """The shared-params/embed_fn path (pipelined embedding + tied-head
    grads, psum-reconciled across stages) must match the AD driver
    value-for-value — loss, stage grads, AND shared grads."""
    rng = np.random.RandomState(4)
    ws = jnp.asarray(rng.randn(PP, D, D) * 0.1, jnp.float32)
    emb = jnp.asarray(rng.randn(16, D) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.randint(0, 16, (8, MB)), jnp.int32)

    def embed_fn(shared, mb):
        return jnp.take(shared["e"], mb, axis=0)

    def loss_fn(shared, y, m):
        # tied head: project back onto the embedding
        return jnp.mean((y @ shared["e"].T) ** 2)

    def run(memory_efficient):
        def inner(ws, shared):
            return forward_backward_pipelining_without_interleaving(
                _stage_fn, micro, {"w": ws[0]},
                loss_fn=loss_fn, shared_params=shared, embed_fn=embed_fn,
                memory_efficient=memory_efficient)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P("pipe"), {"e": P()}),
                         out_specs=(P(), ({"w": P("pipe")}, {"e": P()})))(
                             ws, {"e": emb})

    loss_a, (sg_a, shg_a) = run(True)
    loss_b, (sg_b, shg_b) = run(False)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sg_a["w"]), np.asarray(sg_b["w"]),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(shg_a["e"]),
                               np.asarray(shg_b["e"]),
                               rtol=1e-5, atol=1e-7)


def test_memory_efficient_interleaved_is_O1_in_microbatches(mesh):
    """The interleaved (vpp) driver holds O(L = pp*vpp) activations
    regardless of M, like the single-chunk case."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving)

    VPP = 2
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(PP, VPP, D, D) * 0.1, jnp.float32)

    def temp_bytes(M):
        micro = jnp.asarray(rng.randn(M, MB, D), jnp.float32)

        def run(ws):
            def inner(ws):
                return forward_backward_pipelining_with_interleaving(
                    _stage_fn, micro, {"w": ws[0]},
                    loss_fn=lambda y, m: jnp.mean(y ** 2),
                    num_model_chunks=VPP)
            return shard_map(inner, mesh=mesh, in_specs=(P("pipe"),),
                             out_specs=(P(), {"w": P("pipe")}))(ws)

        compiled = jax.jit(run).lower(ws).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    t8, t32 = temp_bytes(8), temp_bytes(32)
    act_bytes = MB * D * 4
    slope = (t32 - t8) / 24
    assert slope < act_bytes / 4, (t8, t32)


def test_interleaved_num_model_chunks_one(mesh):
    """Regression: the interleaved API with num_model_chunks=1 (params
    carrying the documented leading (1, ...) chunk axis) must work under
    the memory-efficient default and match the AD driver."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving)

    rng = np.random.RandomState(5)
    ws = jnp.asarray(rng.randn(PP, 1, D, D) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.randn(8, MB, D), jnp.float32)

    def run(memory_efficient):
        def inner(ws):
            return forward_backward_pipelining_with_interleaving(
                _stage_fn, micro, {"w": ws[0]},
                loss_fn=lambda y, m: jnp.mean(y ** 2),
                num_model_chunks=1, memory_efficient=memory_efficient)
        return shard_map(inner, mesh=mesh, in_specs=(P("pipe"),),
                         out_specs=(P(), {"w": P("pipe")}))(ws)

    loss_a, grads_a = run(True)
    loss_b, grads_b = run(False)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads_a["w"]),
                               np.asarray(grads_b["w"]),
                               rtol=1e-5, atol=1e-6)
