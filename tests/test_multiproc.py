"""Multi-host elastic runtime: launcher/supervisor, DCN layout, ZeRO
dp-reshard, and the world-size-change restore paths.

The REAL 2-process legs (rendezvous over ``jax.distributed.initialize``,
kill-one-process shrink-resume) run in the driver's multichip gate
(``__graft_entry__._mp_worker``) — subprocess jax worlds are too heavy
for tier-1. Here the same machinery is proven in-process:

- the supervisor (:class:`~apex_tpu.elastic.launch.LocalLauncher`) on
  **stub workers** (plain python, no jax): restart-with-backoff, shrink,
  heartbeat timeout, teardown escalation, ``elastic/*`` metrics;
- the **dp-reshard math** (:mod:`apex_tpu.elastic.reshard`) element-
  identically, including padding changes, growth, and pp/tp columns;
- the **simulated shrink suite**: a real bucket-major ZeRO GPT state
  trained at dp=4 restored by an :class:`ElasticRunner` onto a dp=2
  mesh — flat-vector content element-identical, and the post-shrink
  loss trajectory matching an uninterrupted dp=2 run;
- the :class:`ShardedIndexIterator` ``num_hosts`` guard + ``reseek``
  path, the checkpointer's deterministic retry jitter, the two-signal
  drain escalation, and the DCN device-grid rule.
"""

import os
import signal
import sys
import textwrap
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.elastic import (AsyncCheckpointer, DrainInterrupt,
                              ElasticRunner, FaultPlan, Heartbeat,
                              LocalLauncher, PrefetchingIterator,
                              ShardedIndexIterator, token_batch_fetcher)
from apex_tpu.elastic.reshard import (flat_grid, from_natural,
                                      reshard_flat, shard_permutation,
                                      to_natural)
from apex_tpu.observability.registry import MetricsRegistry
from apex_tpu.parallel import multiproc
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.parallel_state import _dcn_device_grid


# ---------------------------------------------------------------------------
# multiproc: env protocol (no backend use)
# ---------------------------------------------------------------------------

class TestMultiprocEnv:
    def test_process_env_roundtrip(self, monkeypatch):
        env = multiproc.process_env(1, 2, "127.0.0.1:5555",
                                    local_devices=4, run_dir="/r")
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        assert multiproc.process_id() == 1
        assert multiproc.process_count() == 2

    def test_initialize_from_env_is_noop_without_coordinator(
            self, monkeypatch):
        monkeypatch.delenv(multiproc.ENV_COORDINATOR, raising=False)
        assert multiproc.initialize_from_env() is None

    def test_validation(self):
        with pytest.raises(ValueError, match="rank"):
            multiproc.process_env(2, 2, "x:1")
        with pytest.raises(ValueError, match="coordinator"):
            multiproc.initialize(None, 2, 0)
        with pytest.raises(ValueError, match="rank"):
            multiproc.initialize("x:1", 2, 5)

    def test_any_process_single_world(self):
        assert multiproc.any_process(True) is True
        assert multiproc.any_process(False) is False


# ---------------------------------------------------------------------------
# parallel_state: the dp-outermost-over-DCN grid rule
# ---------------------------------------------------------------------------

def _stub_devices(nproc, per):
    return [SimpleNamespace(process_index=p, id=p * 131072 + i)
            for p in range(nproc) for i in range(per)]


class TestDcnGrid:
    def test_dp_spans_processes_tp_pp_stay_inside(self):
        devs = _stub_devices(2, 4)
        grid = _dcn_device_grid(devs, tp=2, pp=2, cp=1, dp=2)
        assert grid.shape == (2, 2, 1, 2)  # (pp, dp, cp, tp)
        for p in range(2):
            for t in range(2):
                # the dp fiber crosses the process boundary...
                assert [grid[p, d, 0, t].process_index
                        for d in range(2)] == [0, 1]
        for d in range(2):
            # ...and each dp rank's (pp x tp) block is one process
            procs = {grid[p, d, 0, t].process_index
                     for p in range(2) for t in range(2)}
            assert procs == {d}

    def test_dp_larger_than_process_count_is_process_major(self):
        """dp=4 over 2 processes: data index d's process is d//dp_local,
        so a host's data-axis block is CONTIGUOUS — the property the
        per-host contiguous batch slices rely on."""
        devs = _stub_devices(2, 4)
        grid = _dcn_device_grid(devs, tp=1, pp=2, cp=1, dp=4)
        for d in range(4):
            procs = {grid[p, d, 0, 0].process_index for p in range(2)}
            assert procs == {d // 2}, (d, procs)

    def test_validation(self):
        devs = _stub_devices(3, 4)
        with pytest.raises(RuntimeError, match="divisible by the process"):
            _dcn_device_grid(devs, tp=1, pp=1, cp=1, dp=4)
        devs = _stub_devices(2, 4)
        with pytest.raises(RuntimeError, match="inside one process"):
            _dcn_device_grid(devs, tp=4, pp=2, cp=1, dp=2)
        uneven = (_stub_devices(1, 4)
                  + [SimpleNamespace(process_index=1, id=9)])
        with pytest.raises(RuntimeError, match="uneven"):
            _dcn_device_grid(uneven, tp=1, pp=1, cp=1, dp=5)

    def test_single_process_default_keeps_legacy_layout(self):
        """dcn auto-detection must not move a single-process mesh: every
        existing single-host layout (and checkpoint) depends on the
        legacy (pp, dp, cp, tp) reshape."""
        devs = jax.devices()[:8]
        legacy = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2,
            pipeline_model_parallel_size=2, devices=devs)
        legacy_grid = np.asarray(legacy.devices).copy()
        parallel_state.destroy_model_parallel()
        auto = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2,
            pipeline_model_parallel_size=2, devices=devs,
            dcn_data_parallel=None)
        try:
            assert (np.asarray(auto.devices) == legacy_grid).all()
        finally:
            parallel_state.destroy_model_parallel()

    def test_explicit_dcn_on_single_process_builds_valid_mesh(self):
        devs = jax.devices()[:8]
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2,
            pipeline_model_parallel_size=2, devices=devs,
            dcn_data_parallel=True)
        try:
            assert dict(mesh.shape) == {"pipe": 2, "data": 2,
                                        "context": 1, "tensor": 2}
            assert {d.id for d in mesh.devices.flat} == \
                {d.id for d in devs}
        finally:
            parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# reshard: the bucket-major dp re-partition math
# ---------------------------------------------------------------------------

class TestReshardMath:
    def test_shard_permutation_is_a_permutation(self):
        idx = shard_permutation(37, 4, 32)
        padded, _ = flat_grid(37, 4, 32)
        assert sorted(idx) == list(range(padded))

    @pytest.mark.parametrize("total,dp_old,dp_new,bb,pp,tp", [
        (37, 4, 2, 32, 1, 1),    # padding shrinks 40 -> 38
        (37, 2, 4, 32, 1, 1),    # grow
        (64, 4, 2, None, 1, 1),  # monolithic
        (50, 4, 2, 0, 2, 2),     # sidecar-spelled monolithic + columns
        (101, 4, 2, 48, 2, 1),   # ragged tail bucket + pp columns
    ])
    def test_reshard_is_element_identical(self, total, dp_old, dp_new,
                                          bb, pp, tp):
        rng = np.random.RandomState(0)
        padded_old, _ = flat_grid(total, dp_old, bb)
        cols = [rng.randn(total).astype(np.float32)
                for _ in range(pp * tp)]
        glob = np.stack([from_natural(c, dp_old, bb) for c in cols]) \
            .reshape(pp, tp, dp_old, padded_old // dp_old) \
            .transpose(0, 2, 1, 3).reshape(-1)
        new = reshard_flat(glob, total=total, dp_old=dp_old,
                           dp_new=dp_new, bucket_bytes=bb, pp=pp, tp=tp)
        padded_new, _ = flat_grid(total, dp_new, bb)
        back = new.reshape(pp, dp_new, tp, padded_new // dp_new) \
                  .transpose(0, 2, 1, 3).reshape(pp * tp, padded_new)
        for ref, col in zip(cols, back):
            np.testing.assert_array_equal(
                to_natural(col, total, dp_new, bb), ref)

    def test_cross_bucket_grid_reshard(self):
        """bucket_bytes_new re-buckets in the same pass — the
        natural-order pivot makes the grid change free off-line (the
        live bucket_stamp guard refuses exactly this on-line)."""
        nat = np.random.RandomState(1).randn(100).astype(np.float32)
        old = from_natural(nat, 4, 64)
        new = reshard_flat(old, total=100, dp_old=4, dp_new=2,
                           bucket_bytes=64, bucket_bytes_new=128)
        np.testing.assert_array_equal(to_natural(new, 100, 2, 128), nat)

    def test_shape_validation(self):
        with pytest.raises(ValueError, match="shape"):
            reshard_flat(np.zeros(7, np.float32), total=8, dp_old=4,
                         dp_new=2, bucket_bytes=None)
        with pytest.raises(ValueError, match="shape"):
            to_natural(np.zeros(6, np.float32), 8, 4, None)


# ---------------------------------------------------------------------------
# the simulated shrink suite (tier-1 acceptance criterion)
# ---------------------------------------------------------------------------

SEQ, MB, GB_ROWS = 8, 2, 8  # global batch rows, world-invariant


def _shrink_cfg(dp):
    from apex_tpu.config import (BatchConfig, ModelConfig,
                                 OptimizerConfig, ParallelConfig,
                                 TrainConfig)
    M = GB_ROWS // (MB * dp)
    return TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=32, hidden_size=16,
                          num_layers=1, num_attention_heads=2,
                          max_position_embeddings=SEQ),
        parallel=ParallelConfig(tensor_model_parallel_size=1,
                                pipeline_model_parallel_size=1),
        batch=BatchConfig(global_batch_size=GB_ROWS,
                          micro_batch_size=MB),
        optimizer=OptimizerConfig(name="adam", lr=1e-2, weight_decay=0.0,
                                  zero=1),
        opt_level="O0", ddp_bucket_bytes=512)


def _shrink_run(ckdir, dp, total_steps, registry):
    """One ElasticRunner.fit of the bucket-major ZeRO GPT at ``dp``
    (same GLOBAL batch sequence at every dp)."""
    from apex_tpu.training import GPTHybridTrainer

    cfg = _shrink_cfg(dp)
    mesh = cfg.initialize_mesh(devices=jax.devices()[:dp])
    try:
        trainer = GPTHybridTrainer(cfg, mesh)
        M = GB_ROWS // (MB * dp)
        data = np.random.RandomState(3).randint(0, 32, (64, SEQ + 1))
        # token_batch_fetcher(data, M, rows, seq) with M * rows ==
        # GB_ROWS: the global batch CONTENT is dp-invariant (only the
        # row -> (microbatch, dp-rank) assignment moves, which the mean
        # loss is invariant to up to fp32 reduction order)
        it = PrefetchingIterator(
            ShardedIndexIterator(64, GB_ROWS, seed=9),
            token_batch_fetcher(data, M, GB_ROWS // M, SEQ), depth=1)
        losses = {}
        runner = ElasticRunner(
            trainer, it, str(ckdir), save_interval=1, keep_last=5,
            exit_on_preempt=False, registry=registry,
            on_step=lambda k, lo: losses.__setitem__(k, float(lo)))
        res = runner.fit(total_steps, key=jax.random.PRNGKey(0))
        return res, losses, trainer
    finally:
        parallel_state.destroy_model_parallel()


class TestSimulatedShrink:
    def test_dp4_to_dp2_shrink_resume_matches_uninterrupted(
            self, tmp_path):
        """THE tier-1 shrink proof: a bucket-major ZeRO state trained at
        dp=4 restores onto a dp=2 mesh through the runner's reshard path
        — (1) the re-partitioned flat shards are ELEMENT-IDENTICAL to
        the dp=4 state on the natural flat vector, (2) ``bucket_stamp``
        validation passes on the new grid (the jitted step dispatches),
        and (3) the post-shrink optimizer steps match an uninterrupted
        dp=2 run (documented parity: steps 1..K ran at dp=4, so only
        fp32 reduction order differs)."""
        reg = MetricsRegistry()
        # dp=4 phase: 2 steps, checkpointing every step
        res4, _, _ = _shrink_run(tmp_path / "run", 4, 2, reg)
        master4 = np.asarray(res4.state[2].master)

        # dp=2 shrink-resume from the SAME directory: the runner must
        # detect the dp=4 sidecar world, reshard, and continue to 4
        res2, losses2, _ = _shrink_run(tmp_path / "run", 2, 4, reg)
        assert res2.restored_from == 2 and res2.resharded, res2
        assert reg.snapshot()["resume/reshards"] == 1

        # (1) the reshard transform is element-identical on the natural
        # flat vector (the sidecar's flat_total is authoritative)
        from apex_tpu.checkpoint import read_host_state
        _, host = read_host_state(str(tmp_path / "run"))
        total = int(host["world"]["flat_total"])
        resharded = reshard_flat(master4, total=total, dp_old=4,
                                 dp_new=2, bucket_bytes=512)
        np.testing.assert_array_equal(
            to_natural(resharded, total, 2, 512),
            to_natural(master4, total, 4, 512))

        # (3) uninterrupted dp=2 reference over the same global batches
        reg2 = MetricsRegistry()
        _, losses_ref, _ = _shrink_run(tmp_path / "ref", 2, 4, reg2)
        for k in (3, 4):
            assert abs(losses2[k] - losses_ref[k]) <= \
                2e-3 * max(1.0, abs(losses_ref[k])), (losses2, losses_ref)

    def test_model_axis_change_is_refused(self, tmp_path, monkeypatch):
        """Only the data axis is elastic: a sidecar recording a
        different pp must fail loudly, not mis-reshard."""
        from apex_tpu import checkpoint as _ckpt
        reg = MetricsRegistry()
        _shrink_run(tmp_path / "run", 2, 1, reg)

        real = _ckpt.read_host_state

        def doctored(directory, step=None):
            s, host = real(directory, step)
            host = dict(host)
            host["world"] = dict(host["world"], pp=7)
            return s, host

        monkeypatch.setattr(_ckpt, "read_host_state", doctored)
        with pytest.raises(ValueError, match="only the data axis"):
            _shrink_run(tmp_path / "run", 2, 2, reg)


# ---------------------------------------------------------------------------
# ShardedIndexIterator: the num_hosts guard + reseek (satellite)
# ---------------------------------------------------------------------------

class TestHostGridReseek:
    def test_state_dict_records_the_grid(self):
        it = ShardedIndexIterator(64, 8, seed=2, host_id=1, num_hosts=2)
        state = it.state_dict()
        assert state["num_hosts"] == 2 and state["global_batch"] == 8

    def test_num_hosts_change_rejected_with_the_fix_spelled_out(self):
        a = ShardedIndexIterator(64, 8, seed=2, host_id=0, num_hosts=2)
        next(a), next(a)
        b = ShardedIndexIterator(64, 8, seed=2)
        with pytest.raises(ValueError) as e:
            b.load_state_dict(a.state_dict())
        assert "num_hosts" in str(e.value)
        assert "reseek" in str(e.value)  # the fix, spelled out

    def test_reseek_preserves_the_global_sequence(self):
        """2-host world consumes k batches; the 1-host survivor reseeks
        and its next batch is exactly global batch k — no row skipped or
        double-consumed."""
        hosts = [ShardedIndexIterator(64, 8, seed=2, host_id=h,
                                      num_hosts=2) for h in range(2)]
        consumed_rows = []
        for _ in range(3):
            consumed_rows.append(
                np.concatenate([next(hosts[0]), next(hosts[1])]))
        survivor = ShardedIndexIterator(64, 8, seed=2)
        survivor.reseek(hosts[0].state_dict())
        ref = ShardedIndexIterator(64, 8, seed=2)
        all_batches = [ref.batch_indices(k) for k in range(4)]
        # the pre-shrink consumption covered exactly batches 0..2...
        for got, want in zip(consumed_rows, all_batches):
            np.testing.assert_array_equal(got, want)
        # ...and the survivor continues at batch 3
        np.testing.assert_array_equal(next(survivor), all_batches[3])

    def test_reseek_still_validates_stream_identity(self):
        it = ShardedIndexIterator(64, 8, seed=2)
        with pytest.raises(ValueError, match="seed"):
            it.reseek({"consumed": 1, "seed": 3, "num_hosts": 2,
                       "global_batch": 8})
        with pytest.raises(ValueError, match="global_batch"):
            it.reseek({"consumed": 1, "seed": 2, "num_hosts": 2,
                       "global_batch": 16})
        with pytest.raises(ValueError, match="global_batch"):
            it.load_state_dict({"consumed": 1, "seed": 2, "num_hosts": 1,
                                "global_batch": 16})

    def test_legacy_state_without_grid_fields_still_loads(self):
        it = ShardedIndexIterator(64, 8, seed=2)
        it.load_state_dict({"consumed": 3, "seed": 2})
        assert it.consumed == 3

    def test_prefetching_iterator_delegates(self):
        data = np.random.RandomState(0).randn(64, 4).astype(np.float32)
        mk = lambda h, n: PrefetchingIterator(
            ShardedIndexIterator(64, 8, seed=2, host_id=h, num_hosts=n),
            lambda idx: (np.take(data, idx, 0),), depth=2)
        two = mk(0, 2)
        next(two), next(two)
        state = two.state_dict()
        assert state["num_hosts"] == 2 and state["consumed"] == 2
        one = mk(0, 1)
        with pytest.raises(ValueError, match="reseek"):
            one.load_state_dict(state)
        one.reseek(state)
        ref = ShardedIndexIterator(64, 8, seed=2)
        np.testing.assert_array_equal(np.asarray(next(one)[0]),
                                      data[ref.batch_indices(2)])


# ---------------------------------------------------------------------------
# AsyncCheckpointer: deterministic retry jitter (satellite)
# ---------------------------------------------------------------------------

class TestRetryJitter:
    def _ck(self, tmp_path, **kw):
        return AsyncCheckpointer(str(tmp_path),
                                 registry=MetricsRegistry(), **kw)

    def test_jitter_is_deterministic_per_host_and_step(self, tmp_path):
        a = self._ck(tmp_path, retry_backoff_s=0.1, retry_jitter=0.5,
                     host_id=3)
        b = self._ck(tmp_path, retry_backoff_s=0.1, retry_jitter=0.5,
                     host_id=3)
        sleeps_a = [a._backoff_sleep_s(7, k) for k in (1, 2, 3)]
        assert sleeps_a == [b._backoff_sleep_s(7, k) for k in (1, 2, 3)]
        # the exponential base underneath, jitter bounded at +50%
        for k, s in enumerate(sleeps_a, start=1):
            base = 0.1 * 2 ** (k - 1)
            assert base <= s <= base * 1.5

    def test_hosts_decorrelate(self, tmp_path):
        """The thundering-herd property: different host_ids must NOT
        retry on the same schedule."""
        sleeps = {h: self._ck(tmp_path, retry_backoff_s=0.1,
                              retry_jitter=0.5,
                              host_id=h)._backoff_sleep_s(7, 1)
                  for h in range(4)}
        assert len(set(sleeps.values())) == 4, sleeps

    def test_cap_bounds_the_exponential(self, tmp_path):
        ck = self._ck(tmp_path, retry_backoff_s=1.0,
                      retry_backoff_cap_s=3.0, retry_jitter=0.0)
        assert ck._backoff_sleep_s(0, 10) == 3.0

    def test_legacy_backoff_s_alias(self, tmp_path):
        ck = self._ck(tmp_path, backoff_s=0.02)
        assert ck.retry_backoff_s == 0.02 and ck.backoff_s == 0.02
        with pytest.raises(ValueError, match="spelled twice"):
            self._ck(tmp_path, backoff_s=0.02, retry_backoff_s=0.3)
        with pytest.raises(ValueError, match="cap"):
            self._ck(tmp_path, retry_backoff_s=5.0,
                     retry_backoff_cap_s=1.0)
        # a legacy base ABOVE the default cap predates the cap and must
        # keep constructing (the default cap lifts to the base)
        big = self._ck(tmp_path, backoff_s=60.0)
        assert big.retry_backoff_cap_s == 60.0

    def test_retries_still_converge_with_jitter_on(self, tmp_path):
        reg = MetricsRegistry()
        plan = FaultPlan(save_errors={5: 2})
        ck = AsyncCheckpointer(str(tmp_path), registry=reg,
                               fault_hook=plan.on_save_attempt,
                               retry_backoff_s=0.001, retry_jitter=0.25,
                               host_id=1)
        ck.save({"w": jnp.zeros(3)}, 5, block=True)
        assert reg.snapshot()["ckpt/retries"] == 2

    def test_collective_mode_never_retries(self, tmp_path):
        """A collective save is fenced by named cross-process barriers;
        an asymmetric retry would re-enter the begin barrier while the
        peers wait in the arrays barrier — so collective mode must fail
        FAST on the first transient error (recovery = supervisor gang
        restart), never sleep-and-retry into a deadlock."""
        reg = MetricsRegistry()
        plan = FaultPlan(save_errors={5: 1})  # one async-retryable error
        ck = AsyncCheckpointer(str(tmp_path), registry=reg,
                               fault_hook=plan.on_save_attempt,
                               collective=True, max_retries=3)
        with pytest.raises(OSError, match="never retry"):
            ck.save({"w": jnp.zeros(3)}, 5)
        assert "ckpt/retries" not in reg.snapshot() or \
            reg.snapshot()["ckpt/retries"] == 0


# ---------------------------------------------------------------------------
# ElasticRunner: two-signal drain escalation (satellite)
# ---------------------------------------------------------------------------

class TestTwoSignalDrain:
    def test_second_sigterm_during_drain_raises(self, tmp_path):
        """First SIGTERM = graceful drain-and-checkpoint; a second one
        while the (slowed) final save is in flight must raise
        DrainInterrupt immediately — a stuck save cannot make the job
        unkillable."""
        from test_elastic import ToyTrainer, _toy_data

        plan = FaultPlan(sigterm_at_step=2, slow_save_s=0.5)
        fired = []

        def hook(step, attempt):
            if not fired:  # the preemption save's first attempt:
                fired.append(step)  # deliver the SECOND signal mid-drain
                os.kill(os.getpid(), signal.SIGTERM)
            plan.on_save_attempt(step, attempt)

        ck = AsyncCheckpointer(str(tmp_path), registry=MetricsRegistry(),
                               fault_hook=hook)
        runner = ElasticRunner(
            ToyTrainer(), _toy_data(), str(tmp_path), save_interval=10,
            fault_plan=plan, checkpointer=ck, exit_on_preempt=False,
            registry=MetricsRegistry())
        prev = signal.getsignal(signal.SIGTERM)
        with pytest.raises(DrainInterrupt, match="second termination"):
            runner.fit(6, key=jax.random.PRNGKey(0))
        # the escalation window restored the handler stack on the way out
        assert signal.getsignal(signal.SIGTERM) == prev
        assert fired == [2]

    def test_single_sigterm_stays_graceful(self, tmp_path):
        """The first signal's behavior is unchanged: drain, save,
        return/exit — regression-pinned next to the escalation."""
        from test_elastic import ToyTrainer, _toy_data

        plan = FaultPlan(sigterm_at_step=2, slow_save_s=0.1)
        runner = ElasticRunner(
            ToyTrainer(), _toy_data(), str(tmp_path), save_interval=10,
            fault_plan=plan, exit_on_preempt=False,
            registry=MetricsRegistry())
        res = runner.fit(6, key=jax.random.PRNGKey(0))
        assert res.preempted and res.step == 2
        from apex_tpu.checkpoint import all_steps
        assert all_steps(str(tmp_path)) == [2]


# ---------------------------------------------------------------------------
# FaultPlan.kill_process (tentpole fault extension)
# ---------------------------------------------------------------------------

class TestKillProcess:
    def test_json_roundtrip(self):
        plan = FaultPlan(kill_process={1: 3}, slow_save_s=0.1)
        back = FaultPlan.from_json(plan.to_json())
        assert back == plan and back.kill_process == {1: 3}

    def test_kills_only_the_named_rank_at_its_step(self, monkeypatch):
        kills = []
        monkeypatch.setattr(os, "kill",
                            lambda pid, sig: kills.append((pid, sig)))
        plan = FaultPlan(kill_process={1: 3})
        monkeypatch.setenv(multiproc.ENV_PROCESS_ID, "0")
        plan.before_step(3)
        assert kills == []  # wrong rank
        monkeypatch.setenv(multiproc.ENV_PROCESS_ID, "1")
        plan.before_step(2)
        assert kills == []  # wrong step
        plan.before_step(3)
        assert kills == [(os.getpid(), signal.SIGKILL)]


# ---------------------------------------------------------------------------
# LocalLauncher: supervisor policy on stub (jax-free) workers
# ---------------------------------------------------------------------------

def _stub_worker(body) -> list:
    """argv of a tiny jax-free worker whose body sees RANK/WORLD/RUN."""
    src = textwrap.dedent("""\
        import os, sys, time
        RANK = int(os.environ["APEX_TPU_PROCESS_ID"])
        WORLD = int(os.environ["APEX_TPU_NUM_PROCESSES"])
        RUN = os.environ["APEX_TPU_RUN_DIR"]
        """) + textwrap.dedent(body)
    return [sys.executable, "-c", src]


class TestLocalLauncher:
    def _launcher(self, tmp_path, argv, **kw):
        kw.setdefault("num_processes", 2)
        kw.setdefault("grace_s", 1.0)
        kw.setdefault("restart_backoff_s", 0.05)
        kw.setdefault("registry", MetricsRegistry())
        return LocalLauncher(argv, run_dir=str(tmp_path / "run"), **kw)

    def test_clean_gang_succeeds(self, tmp_path):
        reg = MetricsRegistry()
        launcher = self._launcher(
            tmp_path, _stub_worker("sys.exit(0)\n"), registry=reg)
        report = launcher.run()
        assert report.succeeded and report.world_size == 2
        assert report.restarts == 0 and report.shrinks == 0
        assert [r.cause for r in report.rounds] == ["ok"]
        assert reg.snapshot()["elastic/world_size"] == 2

    def test_transient_failure_restarts_with_backoff(self, tmp_path):
        """A gang that fails once and then succeeds (marker file) takes
        exactly one same-world restart, no shrink."""
        reg = MetricsRegistry()
        body = """\
            flag = os.path.join(RUN, f"tried_{RANK}")
            if not os.path.exists(flag):
                open(flag, "w").close()
                sys.exit(3)
            sys.exit(0)
        """
        launcher = self._launcher(tmp_path, _stub_worker(body),
                                  max_restarts=2, registry=reg)
        report = launcher.run()
        assert report.succeeded and report.world_size == 2
        assert report.restarts == 1 and report.shrinks == 0
        assert [r.cause for r in report.rounds] == ["exit", "ok"]
        snap = reg.snapshot()
        assert snap["elastic/restarts"] == 1
        assert "elastic/shrinks" not in snap or \
            snap["elastic/shrinks"] == 0

    def test_permanent_failure_shrinks_and_survivor_finishes(
            self, tmp_path):
        """Rank 1 dies deterministically at world 2 (the surviving rank
        0 hangs, as a peer of a dead jax rank would); with the restart
        budget exhausted the supervisor tears the gang down — SIGTERM
        then SIGKILL — and relaunches at world 1, which completes."""
        reg = MetricsRegistry()
        body = """\
            if WORLD == 2 and RANK == 1:
                sys.exit(9)
            if WORLD == 2:
                import signal
                signal.signal(signal.SIGTERM, signal.SIG_IGN)  # stuck peer
                time.sleep(600)
            sys.exit(0)
        """
        launcher = self._launcher(tmp_path, _stub_worker(body),
                                  max_restarts=0, registry=reg)
        report = launcher.run()
        assert report.succeeded and report.world_size == 1
        assert report.restarts == 0 and report.shrinks == 1
        first = report.rounds[0]
        assert first.cause == "exit" and first.returncodes[1] == 9
        # the stuck survivor needed the SIGKILL escalation
        assert first.returncodes[0] == -signal.SIGKILL
        assert reg.snapshot()["elastic/shrinks"] == 1
        assert reg.snapshot()["elastic/world_size"] == 1

    def test_heartbeat_timeout_declares_a_hung_rank(self, tmp_path):
        import json
        reg = MetricsRegistry()
        body = """\
            time.sleep(600)  # alive but never beats
        """
        launcher = self._launcher(
            tmp_path, _stub_worker(body), num_processes=1,
            min_processes=1, max_restarts=0, heartbeat_timeout_s=0.6,
            registry=reg)
        report = launcher.run()
        assert not report.succeeded
        assert report.rounds[0].cause == "heartbeat"
        assert reg.snapshot()["elastic/heartbeat_age_s"] > 0.6
        # a rank wedged BEFORE its first beat is still nameable: the
        # postmortem ages it from round start (the hang detector's own
        # clock) instead of dissolving into "unknown"
        pm = json.load(open(report.rounds[0].postmortem))
        assert pm["culprit_rank"] == 0
        assert pm["culprit_reason"] == "heartbeat_dead"

    def test_worker_heartbeats_keep_the_round_alive(self, tmp_path):
        """A worker alive LONGER than the heartbeat budget survives as
        long as it keeps beating. The stub speaks the on-disk protocol
        directly (atomic tmp+rename into run_dir/hb/rank_<r>) — which
        also pins that protocol: Heartbeat and this writer must agree."""
        body = """\
            hb = os.path.join(RUN, "hb", f"rank_{RANK}")
            os.makedirs(os.path.dirname(hb), exist_ok=True)
            for k in range(14):
                with open(hb + ".tmp", "w") as f:
                    f.write(f"{k} {time.time()}\\n")
                os.replace(hb + ".tmp", hb)
                time.sleep(0.2)
            sys.exit(0)
        """
        launcher = self._launcher(
            tmp_path, _stub_worker(body), num_processes=1,
            max_restarts=0, min_processes=1, heartbeat_timeout_s=1.5)
        report = launcher.run()
        assert report.succeeded  # ~2.8s of life under a 1.5s hb budget
        # both sides agree on the format: the supervisor-side reader
        # decodes the stub's last write
        assert Heartbeat.last_step(str(tmp_path / "run"), 0) == 13

    def test_exhausted_policy_reports_failure_with_forensics(
            self, tmp_path):
        """Policy exhaustion is an OUTCOME (failed report, CLI exit 1),
        not an exception — and the report carries the per-round
        forensics plus per-round worker logs on disk."""
        launcher = self._launcher(tmp_path, _stub_worker("sys.exit(5)\n"),
                                  max_restarts=0, min_processes=2)
        report = launcher.run()
        assert not report.succeeded
        assert report.world_size == 2  # the last world actually run
        # exhausting the policy AT min_processes is not a shrink: no
        # smaller gang ever launched, so none may be counted/emitted
        assert report.shrinks == 0
        assert [r.cause for r in report.rounds] == ["exit"]
        assert report.rounds[0].returncodes[0] == 5
        logs = os.listdir(os.path.join(str(tmp_path / "run"), "logs"))
        assert any(l.startswith("round0_rank") for l in logs)

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError):
            self._launcher(tmp_path, ["x"], num_processes=0)
        with pytest.raises(ValueError):
            self._launcher(tmp_path, ["x"], num_processes=2,
                           min_processes=3)


class TestProgressStall:
    """Satellite: liveness is not progress — a rank whose heartbeat
    mtime keeps moving but whose reported step never advances must be
    flagged within the round budget."""

    def _launcher(self, tmp_path, argv, **kw):
        kw.setdefault("num_processes", 1)
        kw.setdefault("min_processes", 1)
        kw.setdefault("max_restarts", 0)
        kw.setdefault("grace_s", 1.0)
        kw.setdefault("registry", MetricsRegistry())
        return LocalLauncher(argv, run_dir=str(tmp_path / "run"), **kw)

    def test_beating_but_stuck_rank_is_declared_stalled(self, tmp_path):
        """Constant-step heartbeats forever: the worker is perfectly
        alive (old detector: healthy forever) but makes no progress —
        cause "stall" within the heartbeat budget, and the postmortem
        names it with reason stalled_step."""
        body = """\
            hb = os.path.join(RUN, "hb", f"rank_{RANK}")
            os.makedirs(os.path.dirname(hb), exist_ok=True)
            for _ in range(200):
                with open(hb + ".tmp", "w") as f:
                    f.write(f"7 {time.time()}\\n")  # step NEVER moves
                os.replace(hb + ".tmp", hb)
                time.sleep(0.1)
        """
        launcher = self._launcher(tmp_path, _stub_worker(body),
                                  heartbeat_timeout_s=0.8)
        report = launcher.run()
        assert not report.succeeded
        assert report.rounds[0].cause == "stall"
        import json
        pm = json.load(open(report.rounds[0].postmortem))
        assert pm["culprit_rank"] == 0
        assert pm["culprit_reason"] == "stalled_step"
        assert pm["ranks"][0]["stalled"] is True

    # NOTE: the advancing-step twin (a worker alive LONGER than the
    # budget whose step keeps moving must survive) is
    # TestLocalLauncher.test_worker_heartbeats_keep_the_round_alive
    # above — its stub advances the step every beat, so it now pins the
    # progress detector's negative case too.

    def test_step_free_heartbeats_are_exempt(self, tmp_path):
        """A writer speaking only the mtime protocol (no parseable
        step) must not be declared stalled — liveness detection is all
        the supervisor can honestly do for it."""
        body = """\
            hb = os.path.join(RUN, "hb", f"rank_{RANK}")
            os.makedirs(os.path.dirname(hb), exist_ok=True)
            for _ in range(10):
                with open(hb + ".tmp", "w") as f:
                    f.write("alive\\n")  # no step field
                os.replace(hb + ".tmp", hb)
                time.sleep(0.2)
            sys.exit(0)
        """
        launcher = self._launcher(tmp_path, _stub_worker(body),
                                  heartbeat_timeout_s=0.8)
        assert launcher.run().succeeded


class TestLauncherPostmortem:
    def test_failed_round_writes_artifacts_naming_the_dead_rank(
            self, tmp_path):
        """The kill-rank picture in miniature: rank 1 dies on its own,
        rank 0 hangs (as a peer of a dead jax rank would) and gets the
        SUPERVISOR's kill at teardown — the postmortem must blame rank
        1 (pre-teardown exit code), not the framed survivor."""
        import json
        body = """\
            if WORLD == 2 and RANK == 1:
                sys.exit(9)
            if WORLD == 2:
                import signal
                signal.signal(signal.SIGTERM, signal.SIG_IGN)
                time.sleep(600)
            sys.exit(0)
        """
        launcher = LocalLauncher(
            _stub_worker(body), num_processes=2, min_processes=1,
            max_restarts=0, grace_s=1.0, restart_backoff_s=0.05,
            run_dir=str(tmp_path / "run"), registry=MetricsRegistry())
        report = launcher.run()
        assert report.succeeded and report.shrinks == 1
        first = report.rounds[0]
        assert first.cause == "exit" and first.postmortem
        pm = json.load(open(first.postmortem))
        assert pm["culprit_rank"] == 1
        assert pm["culprit_reason"] == "heartbeat_dead"
        ranks = {r["rank"]: r for r in pm["ranks"]}
        assert ranks[1]["returncode"] == 9
        # the survivor was alive pre-teardown: no exit code pinned on it
        assert ranks[0]["returncode"] is None
        # markdown twin next to the JSON
        assert os.path.exists(first.postmortem[:-5] + ".md")
        # the successful world-1 round writes none
        assert report.rounds[1].cause == "ok"
        assert report.rounds[1].postmortem is None


class TestLauncherMetricsEndpoint:
    def test_live_scrape_serves_merged_registry(self, tmp_path):
        """metrics_port=0: while the gang runs, /metrics serves the
        supervisor's elastic/ metrics MERGED with every rank's
        published snapshot (counters summed), and /fleet returns the
        raw merged JSON."""
        import json
        import threading
        import time
        import urllib.request

        body = """\
            from apex_tpu.observability.fleet import FleetPublisher
            from apex_tpu.observability.registry import MetricsRegistry
            reg = MetricsRegistry()
            reg.counter("train/steps").inc(1)
            hb = os.path.join(RUN, "hb", f"rank_{RANK}")
            os.makedirs(os.path.dirname(hb), exist_ok=True)
            with open(hb + ".tmp", "w") as f:
                f.write(f"1 {time.time()}\\n")
            os.replace(hb + ".tmp", hb)
            FleetPublisher(RUN, rank=RANK, registry=reg).publish(
                1, force=True)
            time.sleep(2.0)
            sys.exit(0)
        """
        src = _stub_worker(body)
        src[-1] = f"import sys; sys.path.insert(0, {os.getcwd()!r})\n" \
            + src[-1]
        launcher = LocalLauncher(
            src, num_processes=2, min_processes=2, max_restarts=0,
            grace_s=1.0, heartbeat_timeout_s=60.0,
            run_dir=str(tmp_path / "run"), registry=MetricsRegistry(),
            metrics_port=0)
        box = {}
        th = threading.Thread(
            target=lambda: box.update(report=launcher.run()))
        th.start()
        try:
            scrape = fleet_doc = None
            deadline = time.monotonic() + 30.0
            while th.is_alive() and time.monotonic() < deadline:
                port = launcher.bound_metrics_port
                if port is not None:
                    try:
                        text = urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics",
                            timeout=2).read().decode()
                        if ("train_steps 2" in text
                                and "elastic_world_size 2" in text):
                            scrape = text
                            fleet_doc = json.loads(
                                urllib.request.urlopen(
                                    f"http://127.0.0.1:{port}/fleet",
                                    timeout=2).read())
                            break
                    except OSError:
                        pass
                time.sleep(0.1)
        finally:
            th.join()
        assert box["report"].succeeded
        assert scrape is not None, "merged families never appeared"
        # counters SUMMED across both ranks, supervisor metrics present
        assert "train_steps 2" in scrape
        assert "fleet_ranks 2" in scrape
        assert fleet_doc["counters"]["train/steps"]["total"] == 2.0
        assert fleet_doc["step_skew"] == 0
        # the server is gone once run() returned
        with pytest.raises(OSError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{launcher.bound_metrics_port}"
                f"/metrics", timeout=0.5)


class TestHeartbeat:
    def test_supervisor_age_is_monotonic_not_wallclock(self, tmp_path):
        """A wall-clock step must not fake staleness: the supervisor
        ages a rank from the MONOTONIC time its heartbeat mtime last
        changed, using the mtime only as a change detector — a file
        stamped 9999s in the past (the NTP-step/VM-resume picture) reads
        as fresh on first observation and ages from there."""
        import time as _time
        launcher = LocalLauncher(["x"], num_processes=1,
                                 run_dir=str(tmp_path / "run"),
                                 registry=MetricsRegistry())
        hb = Heartbeat(str(tmp_path / "run"), 0)
        hb.beat(1)
        past = _time.time() - 9999.0
        os.utime(hb.path, (past, past))
        fake = [SimpleNamespace(poll=lambda: None)]
        seen = {}
        started = _time.monotonic()
        assert launcher._heartbeat_age(fake, started, seen) == 0.0
        assert launcher._heartbeat_age(fake, started, seen) < 5.0

    def test_beat_age_and_last_step(self, tmp_path):
        hb = Heartbeat(str(tmp_path), rank=1)
        assert Heartbeat.age_s(str(tmp_path), 1) is None
        assert Heartbeat.age_s(str(tmp_path), 1, default=7.0) == 7.0
        hb.beat(12)
        age = Heartbeat.age_s(str(tmp_path), 1)
        assert age is not None and age < 5.0
        assert Heartbeat.last_step(str(tmp_path), 1) == 12
        Heartbeat.clear(str(tmp_path))
        assert Heartbeat.age_s(str(tmp_path), 1) is None

    def test_rank_defaults_to_multiproc_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv(multiproc.ENV_PROCESS_ID, "3")
        hb = Heartbeat(str(tmp_path))
        hb.beat(1)
        assert Heartbeat.last_step(str(tmp_path), 3) == 1

    def test_beat_writes_atomic_json_payload(self, tmp_path):
        """Satellite: beat() grows a JSON payload (schema version +
        completed step) next to the mtime touch; last_step prefers it,
        clear removes it with the rest."""
        import json
        hb = Heartbeat(str(tmp_path), rank=0)
        hb.beat(12)
        doc = json.load(open(hb.path + ".json"))
        assert doc["schema"] == Heartbeat.SCHEMA
        assert doc["step"] == 12 and doc["time"] > 0
        assert not os.path.exists(hb.path + ".json.tmp")
        assert Heartbeat.last_step(str(tmp_path), 0) == 12
        Heartbeat.clear(str(tmp_path))
        assert not os.path.exists(hb.path + ".json")

    def test_last_step_falls_back_to_text_protocol(self, tmp_path):
        """External writers that only speak the legacy text format
        (the stub workers above) stay decodable."""
        path = os.path.join(str(tmp_path), "hb", "rank_4")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write("9 1690000000.0\n")
        assert Heartbeat.last_step(str(tmp_path), 4) == 9


# ---------------------------------------------------------------------------
# the CLI surfaces
# ---------------------------------------------------------------------------

class TestCli:
    def test_launch_cli_runs_a_gang(self, tmp_path):
        from apex_tpu.elastic import launch as launch_mod
        rc = launch_mod.main(
            ["-n", "2", "--run-dir", str(tmp_path), "--max-restarts",
             "0", "--", sys.executable, "-c", "pass"])
        assert rc == 0

    def test_launch_cli_maps_policy_exhaustion_to_exit_1(self, tmp_path):
        from apex_tpu.elastic import launch as launch_mod
        rc = launch_mod.main(
            ["-n", "1", "--run-dir", str(tmp_path), "--max-restarts",
             "0", "--", sys.executable, "-c", "import sys; sys.exit(7)"])
        assert rc == 1

    def test_multiproc_cli_delegates(self, tmp_path):
        rc = multiproc.main(
            ["-n", "1", "--run-dir", str(tmp_path), "--",
             sys.executable, "-c", "import sys; sys.exit(0)"])
        assert rc == 0
