"""Ring-decomposed collective matmul (tensor_parallel/collective_matmul).

The acceptance contract of the overlap work (ISSUE 2): the decomposed path
must be numerically interchangeable with the fused collectives — bit-exact
at TP=2, where the two-term fp32 ring sum is commutative — and its jaxpr
must actually BE decomposed: ``tp−1`` ppermutes per ring and no
``all_gather``/``reduce_scatter`` (psum_scatter's primitive name) for the
wired layers. Correctness runs on the CPU mesh; the speedup is measured on
TPU by ``bench.py::bench_gpt_sp_overlap``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear, RowParallelLinear, all_gather_matmul,
    matmul_reduce_scatter)
from apex_tpu.utils.compat import shard_map


@pytest.fixture(params=[2, 4])
def mesh_tp(request):
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=request.param)
    yield mesh, request.param
    parallel_state.destroy_model_parallel()


@pytest.fixture
def mesh_tp2():
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    yield mesh
    parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# primitive-level: values and grads vs the fused reference
# ---------------------------------------------------------------------------

def test_all_gather_matmul_matches_fused(mesh_tp):
    mesh, tp = mesh_tp
    rng = np.random.RandomState(0)
    b, s, din, dout = 2, 8, 8, 8
    x = jnp.asarray(rng.randn(b, s, din), jnp.float32)
    w = jnp.asarray(rng.randn(tp, dout // tp, din), jnp.float32)

    def ring(x, w):
        def inner(x, w):
            return all_gather_matmul(x, w[0], "tensor", 1)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, "tensor", None), P("tensor")),
                         out_specs=P(None, None, "tensor"))(x, w)

    def fused(x, w):
        def inner(x, w):
            xg = jax.lax.all_gather(x, "tensor", axis=1, tiled=True)
            return jax.lax.dot_general(
                xg, w[0], (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, "tensor", None), P("tensor")),
                         out_specs=P(None, None, "tensor"))(x, w)

    y_ring = jax.jit(ring)(x, w)
    y_fused = jax.jit(fused)(x, w)
    # seq chunking never changes a row's contraction: bit-identical at any tp
    np.testing.assert_array_equal(np.asarray(y_ring), np.asarray(y_fused))

    # grads vs the dense TP=1 reference
    def loss_ring(x, w):
        def inner(x, w):
            y = all_gather_matmul(x, w[0], "tensor", 1)
            return jax.lax.psum(jnp.sum(y * y), "tensor")
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, "tensor", None), P("tensor")),
                         out_specs=P())(x, w)

    gx, gw = jax.jit(jax.grad(loss_ring, argnums=(0, 1)))(x, w)
    wfull = jnp.asarray(np.asarray(w).reshape(dout, din))

    def loss_dense(x, wfull):
        y = x @ wfull.T
        return jnp.sum(y * y)

    gxr, gwr = jax.grad(loss_dense, argnums=(0, 1))(x, wfull)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gxr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw).reshape(dout, din),
                               np.asarray(gwr), rtol=1e-5, atol=1e-5)


def test_matmul_reduce_scatter_matches_fused(mesh_tp):
    mesh, tp = mesh_tp
    rng = np.random.RandomState(1)
    b, s, din, dout = 2, 8, 8, 8
    x = jnp.asarray(rng.randn(b, s, din), jnp.float32)
    w = jnp.asarray(rng.randn(tp, dout, din // tp), jnp.float32)
    add = jnp.asarray(rng.randn(dout), jnp.float32)

    def ring(x, w, add):
        def inner(x, w, add):
            return matmul_reduce_scatter(x, w[0], add, "tensor", 1)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, None, "tensor"), P("tensor"),
                                   P()),
                         out_specs=P(None, "tensor", None))(x, w, add)

    def fused(x, w, add):
        def inner(x, w, add):
            part = jax.lax.dot_general(
                x, w[0], (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) + add
            return jax.lax.psum_scatter(part, "tensor",
                                        scatter_dimension=1, tiled=True)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, None, "tensor"), P("tensor"),
                                   P()),
                         out_specs=P(None, "tensor", None))(x, w, add)

    y_ring = jax.jit(ring)(x, w, add)
    y_fused = jax.jit(fused)(x, w, add)
    if tp == 2:
        # two-term fp32 sums are commutative: ring order == psum order
        np.testing.assert_array_equal(np.asarray(y_ring),
                                      np.asarray(y_fused))
    else:
        # documented <=1-ULP-class fp32 reassociation beyond tp=2
        np.testing.assert_allclose(np.asarray(y_ring), np.asarray(y_fused),
                                   rtol=1e-6, atol=1e-6)

    # grads vs the dense reference (each rank's partial carries `add`,
    # so the dense model sees tp*add)
    def loss_ring(x, w, add):
        def inner(x, w, add):
            y = matmul_reduce_scatter(x, w[0], add, "tensor", 1)
            return jax.lax.psum(jnp.sum(y * y), "tensor")
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, None, "tensor"), P("tensor"),
                                   P()),
                         out_specs=P())(x, w, add)

    gx, gw, ga = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(x, w, add)
    wfull = jnp.asarray(np.concatenate(list(np.asarray(w)), axis=1))

    def loss_dense(x, wfull, add):
        y = x @ wfull.T + tp * add
        return jnp.sum(y * y)

    gxr, gwr, gar = jax.grad(loss_dense, argnums=(0, 1, 2))(x, wfull, add)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gxr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(gw)), axis=1), np.asarray(gwr),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gar),
                               rtol=1e-5, atol=1e-4)


def test_matmul_reduce_scatter_scalar_partial_add_grad(mesh_tp2):
    """partial_add is '(out,)-broadcastable': the backward's
    broadcast-transpose must also handle a scalar (sum every axis)."""
    mesh = mesh_tp2
    rng = np.random.RandomState(7)
    tp, b, s, din, dout = 2, 2, 4, 4, 4
    x = jnp.asarray(rng.randn(b, s, din), jnp.float32)
    w = jnp.asarray(rng.randn(tp, dout, din // tp), jnp.float32)
    add = jnp.float32(0.5)

    def loss(x, w, add):
        def inner(x, w, add):
            y = matmul_reduce_scatter(x, w[0], add, "tensor", 1)
            return jax.lax.psum(jnp.sum(y * y), "tensor")
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, None, "tensor"), P("tensor"),
                                   P()),
                         out_specs=P())(x, w, add)

    ga = jax.jit(jax.grad(loss, argnums=2))(x, w, add)
    wfull = jnp.asarray(np.concatenate(list(np.asarray(w)), axis=1))
    gar = jax.grad(
        lambda x, wf, a: jnp.sum((x @ wf.T + tp * a) ** 2),
        argnums=2)(x, wfull, add)
    np.testing.assert_allclose(float(ga), float(gar), rtol=1e-5)


def test_matmul_reduce_scatter_rejects_indivisible_seq(mesh_tp2):
    mesh = mesh_tp2
    x = jnp.ones((2, 7, 4))  # 7 % 2 != 0
    w = jnp.ones((2, 4, 2))

    def run(x, w):
        def inner(x, w):
            return matmul_reduce_scatter(x, w[0], None, "tensor", 1)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, None, "tensor"), P("tensor")),
                         out_specs=P(None, "tensor", None))(x, w)

    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(run)(x, w)


# ---------------------------------------------------------------------------
# jaxpr shape: the decomposition is real (acceptance criterion)
# ---------------------------------------------------------------------------

from _jaxpr_utils import collective_census as _census  # noqa: E402


def test_jaxpr_ring_decomposition_primitives(mesh_tp):
    mesh, tp = mesh_tp
    x = jnp.ones((2, 8, 8), jnp.float32)
    w = jnp.ones((tp, 8 // tp, 8), jnp.float32)

    def fwd(x, w):
        def inner(x, w):
            return all_gather_matmul(x, w[0], "tensor", 1)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, "tensor", None), P("tensor")),
                         out_specs=P(None, None, "tensor"))(x, w)

    c = _census(str(jax.make_jaxpr(fwd)(x, w)))
    assert c == {"ppermute": tp - 1, "all_gather": 0, "reduce_scatter": 0}

    # fwd+bwd: the backward ring (RS of dX) adds its own tp-1 ppermutes
    def loss(x, w):
        def inner(x, w):
            y = all_gather_matmul(x, w[0], "tensor", 1)
            return jax.lax.psum(jnp.sum(y * y), "tensor")
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, "tensor", None), P("tensor")),
                         out_specs=P())(x, w)

    c = _census(str(jax.make_jaxpr(jax.grad(loss, argnums=(0, 1)))(x, w)))
    assert c == {"ppermute": 2 * (tp - 1), "all_gather": 0,
                 "reduce_scatter": 0}


def test_jaxpr_ring_decomposition_wired_layers(mesh_tp2):
    """The SP-wired Column+Row pair, overlap on: fwd+bwd jaxpr holds
    exactly the ring ppermutes (4 rings x (tp-1)) and ZERO fused
    all-gathers/reduce-scatters — the collectives really were replaced,
    not supplemented."""
    mesh = mesh_tp2
    tp, b, s, h = 2, 2, 8, 8
    col = ColumnParallelLinear(h, 2 * h, gather_output=False, world_size=tp,
                               sequence_parallel=True, seq_axis=1,
                               tp_comm_overlap=True)
    row = RowParallelLinear(2 * h, h, input_is_parallel=True, world_size=tp,
                           sequence_parallel=True, seq_axis=1,
                           tp_comm_overlap=True)
    cp = col.init(jax.random.PRNGKey(0))
    rp = row.init(jax.random.PRNGKey(1))
    x = jnp.ones((b, s, h), jnp.float32)

    def loss(cp, rp, x):
        def inner(cp, rp, x):
            y, _ = col(cp, x)
            out, _ = row(rp, y)
            return jax.lax.psum(jnp.sum(out * out), "tensor")
        return shard_map(inner, mesh=mesh,
                         in_specs=(P("tensor"), P("tensor"),
                                   P(None, "tensor", None)),
                         out_specs=P())(cp, rp, x)

    c = _census(str(jax.make_jaxpr(
        jax.grad(loss, argnums=(0, 1)))(cp, rp, x)))
    # fwd: col ring + row ring; bwd: col dX ring + row dX ring
    assert c == {"ppermute": 4 * (tp - 1), "all_gather": 0,
                 "reduce_scatter": 0}, c


# ---------------------------------------------------------------------------
# layer-level: overlap path is bit-identical to the fused SP path at tp=2
# ---------------------------------------------------------------------------

def test_layers_overlap_bit_identical_tp2(mesh_tp2):
    mesh = mesh_tp2
    rng = np.random.RandomState(3)
    tp, b, s, h = 2, 2, 8, 8
    x = jnp.asarray(rng.randn(b, s, h), jnp.float32)

    def build(overlap):
        col = ColumnParallelLinear(h, 2 * h, gather_output=False,
                                   world_size=tp, sequence_parallel=True,
                                   seq_axis=1, tp_comm_overlap=overlap)
        row = RowParallelLinear(2 * h, h, input_is_parallel=True,
                                world_size=tp, sequence_parallel=True,
                                seq_axis=1, tp_comm_overlap=overlap)
        return col, row

    col, row = build(False)
    cp = col.init(jax.random.PRNGKey(0))
    rp = row.init(jax.random.PRNGKey(1))
    rp = {"weight": rp["weight"], "bias": rp["bias"] + 0.25}

    def run(col, row, cp, rp, x):
        def inner(cp, rp, x):
            def loss_of(ps):
                y, _ = col(ps[0], x)
                out, _ = row(ps[1], y)
                return jax.lax.psum(jnp.sum(out * out), "tensor")
            l, g = jax.value_and_grad(loss_of)((cp, rp))
            pm = lambda v: jax.lax.pmean(v, "tensor")
            return pm(l), jax.tree_util.tree_map(pm, g)
        specs = {"weight": P("tensor"), "bias": P("tensor")}
        return shard_map(inner, mesh=mesh,
                         in_specs=(specs, specs, P(None, "tensor", None)),
                         out_specs=(P(), (specs, specs)))(cp, rp, x)

    l_f, g_f = jax.jit(lambda *a: run(*build(False), *a))(cp, rp, x)
    l_o, g_o = jax.jit(lambda *a: run(*build(True), *a))(cp, rp, x)
    assert float(l_o) == float(l_f)
    # weight/input grads are bit-identical; the bias-fold cotangent is the
    # same full-sequence sum computed in a different XLA fusion, which may
    # reassociate the reduction — the documented <=1-ULP fp32 delta
    # (docs/PERF.md "Dependent-collective overlap")
    for a, b_ in zip(jax.tree_util.tree_leaves(g_o),
                     jax.tree_util.tree_leaves(g_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-7, atol=0)


# ---------------------------------------------------------------------------
# model-level: GPT SP+overlap == GPT SP == plain TP (the existing contract)
# ---------------------------------------------------------------------------

def test_gpt_sp_overlap_matches_sp_and_tp(mesh_tp2):
    from apex_tpu.models import GPTConfig, GPTModel

    mesh = mesh_tp2
    kw = dict(vocab_size=128, hidden_size=32, num_layers=2,
              num_attention_heads=4, max_position_embeddings=16,
              compute_dtype=jnp.float32, use_flash=False,
              tensor_model_parallel_size=2)
    m_tp = GPTModel(GPTConfig(**kw))
    m_sp = GPTModel(GPTConfig(**kw, sequence_parallel=True))
    m_ov = GPTModel(GPTConfig(**kw, sequence_parallel=True,
                              tp_comm_overlap=True))
    params = m_tp.init(jax.random.PRNGKey(2))
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, 128, (2, 16)))
    specs = m_tp.param_specs(params)

    def run(model, params, tokens):
        def inner(params, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: model.loss(p, tokens, tokens))(params)
            pm = lambda v: jax.lax.pmean(
                jax.lax.pmean(v, "tensor"), "data")
            return pm(loss), jax.tree_util.tree_map(pm, grads)
        return shard_map(inner, mesh=mesh, in_specs=(specs, P()),
                         out_specs=(P(), specs))(params, tokens)

    loss_tp, g_tp = jax.jit(lambda p, t: run(m_tp, p, t))(params, tokens)
    loss_sp, g_sp = jax.jit(lambda p, t: run(m_sp, p, t))(params, tokens)
    loss_ov, g_ov = jax.jit(lambda p, t: run(m_ov, p, t))(params, tokens)

    # overlap vs fused SP: bit-identical at tp=2 (loss AND every grad leaf)
    assert float(loss_ov) == float(loss_sp)
    for a, b in zip(jax.tree_util.tree_leaves(g_ov),
                    jax.tree_util.tree_leaves(g_sp)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # overlap vs plain TP: the existing SP-vs-TP tolerance contract
    np.testing.assert_allclose(float(loss_ov), float(loss_tp), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_ov),
                    jax.tree_util.tree_leaves(g_tp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_gpt_config_overlap_requires_sequence_parallel():
    from apex_tpu.models import GPTConfig, GPTModel

    with pytest.raises(ValueError, match="sequence_parallel"):
        GPTModel(GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                           num_attention_heads=4,
                           tensor_model_parallel_size=2,
                           tp_comm_overlap=True))
    # the layers refuse the combination directly too (no silent
    # fall-through to the fused path for direct layer users)
    with pytest.raises(ValueError, match="sequence_parallel"):
        ColumnParallelLinear(8, 8, world_size=2, tp_comm_overlap=True)
    with pytest.raises(ValueError, match="sequence_parallel"):
        RowParallelLinear(8, 8, world_size=2, tp_comm_overlap=True)


# ---------------------------------------------------------------------------
# trainer wiring: SP(+overlap) through TrainConfig at pp=1, with telemetry
# ---------------------------------------------------------------------------

def _trainer_cfg(sp, ov):
    from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                                 ParallelConfig, TrainConfig)

    return TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=64, hidden_size=32,
                          num_layers=2, num_attention_heads=4,
                          max_position_embeddings=8,
                          sequence_parallel=sp, tp_comm_overlap=ov),
        parallel=ParallelConfig(tensor_model_parallel_size=2),
        batch=BatchConfig(global_batch_size=16, micro_batch_size=2),
        optimizer=OptimizerConfig(name="adam", lr=1e-3),
        opt_level="O0")


def test_hybrid_trainer_sp_refused_on_pre_vma_jax():
    """The trainer's step runs under shard_map_unchecked; without the VMA
    replication rewrite the SP cotangent flow is silently wrong (partial
    LN/position grads), so construction must refuse loudly on 0.4.x."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.utils.compat import HAS_VMA

    if HAS_VMA:
        pytest.skip("VMA jax: SP through the trainer is supported")
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    try:
        with pytest.raises(NotImplementedError, match="silently wrong"):
            GPTHybridTrainer(_trainer_cfg(True, True), mesh)
        # non-SP construction stays fine
        GPTHybridTrainer(_trainer_cfg(False, False), mesh)
    finally:
        parallel_state.destroy_model_parallel()


def test_hybrid_trainer_sp_overlap_step_and_metrics():
    """VMA jax only: SP(+overlap) trainer parity vs the NON-SP trainer —
    loss AND one-step updated params/first moments (losses alone would
    slip wrong gradients), plus the tp/* telemetry."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.utils.compat import HAS_VMA

    if not HAS_VMA:
        pytest.skip("pre-VMA jax: SP through the trainer is refused "
                    "(test_hybrid_trainer_sp_refused_on_pre_vma_jax)")

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (4, 8, 8)))
    targets = jnp.asarray(rng.randint(0, 64, (4, 8, 8)))

    results = {}
    for name, (sp, ov) in {"tp": (False, False), "sp": (True, False),
                           "ov": (True, True)}.items():
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2)
        try:
            tr = GPTHybridTrainer(_trainer_cfg(sp, ov), mesh)
            state = tr.init_state(jax.random.PRNGKey(0))
            loss, stage, shared, opt_state, _, metrics = jax.jit(
                tr.train_step_with_metrics)(*state, tokens, targets)
            results[name] = (float(loss), (stage, shared),
                             opt_state.exp_avg, metrics.as_floats())
        finally:
            parallel_state.destroy_model_parallel()

    assert results["ov"][0] == results["sp"][0]
    np.testing.assert_allclose(results["sp"][0], results["tp"][0],
                               rtol=1e-5)
    # gradients, not just losses: post-step params and adam first moments
    # of the SP legs must match the non-SP trainer ground truth
    for leg in ("sp", "ov"):
        for a, b in zip(jax.tree_util.tree_leaves(results[leg][1]),
                        jax.tree_util.tree_leaves(results["tp"][1])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(results[leg][2]),
                        jax.tree_util.tree_leaves(results["tp"][2])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=1e-4)
    m = results["ov"][3]
    assert m["tp/overlap_chunks"] == 2.0
    # M=4 microbatches x 2 layers x (tp-1) x 4 rings x (2*4*32 elems x 4B)
    # per rank, psummed over the 8 mesh devices
    assert m["tp/collective_bytes"] == 4 * 2 * (2 * 1024 + 2 * 1024) * 8
    assert "tp/overlap_chunks" not in results["sp"][3]


def test_model_level_tp_overlap_metrics():
    """tp/* telemetry through the model path (transform), which runs under
    plain full-checking shard_map and is supported on any jax version."""
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.observability import ingraph

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    try:
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=8,
                        compute_dtype=jnp.float32, use_flash=False,
                        tensor_model_parallel_size=2,
                        sequence_parallel=True, tp_comm_overlap=True)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 64, (2, 8)))
        specs = model.param_specs(params)

        def run(params, tokens):
            def inner(params, tokens):
                out, metrics = ingraph.reap(
                    lambda: model.loss(params, tokens, tokens))()
                pm = lambda v: jax.lax.pmean(
                    jax.lax.pmean(v, "tensor"), "data")
                return pm(out), ingraph.aggregate(
                    metrics, ("data", "tensor"))
            return shard_map(inner, mesh=mesh, in_specs=(specs, P()),
                             out_specs=(P(), P()))(params, tokens)

        loss, metrics = jax.jit(run)(params, tokens)
        got = metrics.as_floats()
        assert got["tp/overlap_chunks"] == 2.0
        # 2 layers x (tp-1) x (2 col + 2 row rings) x (2*4*32 elems x 4B)
        # per rank, psummed over the 8 mesh devices
        assert got["tp/collective_bytes"] == 2 * (2 * 1024 + 2 * 1024) * 8
        assert np.isfinite(float(loss))
    finally:
        parallel_state.destroy_model_parallel()
