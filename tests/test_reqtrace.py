"""Request-lifecycle tracing, latency percentiles, and SLO goodput
(docs/OBSERVABILITY.md "Serving latency & SLO", docs/SERVING.md):
histogram percentile math vs numpy, the bounded request ring + its
concurrency contract, strict-JSON Chrome swimlane export, measured
scheduler latencies, SLO goodput/burn-rate + the flight-recorder dump,
and the tracing-off zero-cost assertions."""

import io
import json
import math
import threading

import numpy as np

import jax
import pytest

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.observability import JSONLSink, StepReporter
from apex_tpu.observability.registry import (Histogram, MetricsRegistry,
                                             log_buckets)
from apex_tpu.observability.reqtrace import (LATENCY_BUCKETS_MS,
                                             RequestRecord, RequestTrace,
                                             chrome_request_trace)
from apex_tpu.observability.slo import (SLOTarget, SLOTracker,
                                        SLOViolationError)
from apex_tpu.serving import Request, ServingEngine, SlotScheduler


# ---------------------------------------------------------------------------
# log-spaced buckets + percentile readout
# ---------------------------------------------------------------------------

class TestLogBuckets:
    def test_endpoints_count_and_monotone(self):
        b = log_buckets(0.1, 1000.0, 9)
        assert len(b) == 9
        assert b[0] == pytest.approx(0.1) and b[-1] == pytest.approx(1000.0)
        assert all(hi > lo for lo, hi in zip(b, b[1:]))
        # constant ratio — the documented resolution property
        ratios = [hi / lo for lo, hi in zip(b, b[1:])]
        np.testing.assert_allclose(ratios, ratios[0], rtol=1e-12)

    def test_validation(self):
        for lo, hi, n in ((0.0, 1.0, 4), (-1.0, 1.0, 4), (2.0, 1.0, 4),
                          (1.0, 2.0, 1)):
            with pytest.raises(ValueError):
                log_buckets(lo, hi, n)


class TestHistogramPercentile:
    @pytest.mark.parametrize("dist", ["lognormal", "uniform"])
    def test_vs_numpy_quantile_within_bucket_resolution(self, dist):
        """The documented error bound: a percentile interpolated inside
        one log bucket is within (r - 1) relative of numpy's exact
        quantile, r the adjacent-bound ratio."""
        rng = np.random.RandomState(0)
        if dist == "lognormal":
            samples = rng.lognormal(3.0, 1.0, 5000)
        else:
            samples = rng.uniform(2.0, 500.0, 5000)
        bounds = log_buckets(samples.min() * 0.9, samples.max() * 1.1, 200)
        r = (bounds[-1] / bounds[0]) ** (1.0 / (len(bounds) - 1))
        h = Histogram("x", bounds)
        for s in samples:
            h.observe(s)
        for q in (1, 25, 50, 90, 95, 99, 99.9):
            true = float(np.percentile(samples, q))
            assert abs(h.percentile(q) - true) <= (r - 1.0) * true + 1e-9

    def test_small_windows_track_numpy_convention(self):
        """The bench legs read p95/p99 off a handful of requests: at
        small n the estimator must follow numpy's rank convention (an
        outlier max must not swallow p95), staying inside the (r - 1)
        relative bound."""
        rng = np.random.RandomState(7)
        bounds = log_buckets(1e-2, 6e4, 68)
        r = (bounds[-1] / bounds[0]) ** (1.0 / (len(bounds) - 1))
        for _ in range(200):
            n = rng.randint(2, 40)
            samples = np.clip(
                rng.lognormal(rng.uniform(1, 8), rng.uniform(0.3, 2), n),
                bounds[0], bounds[-1])
            h = Histogram("x", bounds)
            for s in samples:
                h.observe(s)
            for q in (5, 50, 95, 99):
                true = float(np.percentile(samples, q))
                assert abs(h.percentile(q) - true) <= (r - 1) * true + 1e-9
        # the outlier shape: one huge sample must not drag p95 to it
        s = np.concatenate([rng.uniform(100, 5000, 17), [24000.0]])
        h = Histogram("x", bounds)
        for v in s:
            h.observe(v)
        assert abs(h.percentile(95) - np.percentile(s, 95)) \
            <= (r - 1) * np.percentile(s, 95)

    def test_edges(self):
        h = Histogram("x", log_buckets(1.0, 100.0, 10))
        assert math.isnan(h.percentile(50))  # empty
        h.observe(7.0)
        for q in (0, 50, 100):  # single sample: every quantile is it
            assert h.percentile(q) == 7.0
        h.observe(70.0)
        assert h.percentile(0) == 7.0 and h.percentile(100) == 70.0
        # monotone in q
        qs = [h.percentile(q) for q in range(0, 101, 5)]
        assert all(b >= a for a, b in zip(qs, qs[1:]))
        with pytest.raises(ValueError):
            h.percentile(101)
        with pytest.raises(ValueError):
            h.percentile(-1)

    def test_out_of_range_samples_clamp_to_observed(self):
        """Samples past the last bound (the +inf overflow bucket) and
        below the first bound still yield finite percentiles clamped to
        the observed min/max — no fabricated +inf p99."""
        h = Histogram("x", log_buckets(1.0, 10.0, 5))
        for v in (0.01, 0.02, 5.0, 500.0, 900.0):
            h.observe(v)
        assert h.percentile(0) == 0.01
        assert h.percentile(99) <= 900.0
        assert h.percentile(100) == 900.0
        assert math.isfinite(h.percentile(90))

    def test_reset_clears_percentile_state(self):
        h = Histogram("x", log_buckets(1.0, 10.0, 5))
        h.observe(3.0)
        h.reset()
        assert math.isnan(h.percentile(50))
        h.observe(9.0)
        assert h.percentile(50) == 9.0


# ---------------------------------------------------------------------------
# Prometheus text-format snapshot
# ---------------------------------------------------------------------------

class TestRenderPrometheus:
    def test_counter_gauge_histogram_series(self):
        reg = MetricsRegistry()
        reg.counter("serve/admitted").inc(3)
        reg.gauge("slo/goodput").set(0.97)
        reg.gauge("never/set")  # unset: must not render
        reg.histogram("serve/ttft_ms", (1.0, 10.0)).observe(5.0)
        text = reg.render_prometheus()
        lines = text.splitlines()
        assert "# TYPE serve_admitted counter" in lines
        assert "serve_admitted 3" in lines
        assert "# TYPE slo_goodput gauge" in lines
        assert "slo_goodput 0.97" in lines
        assert not any("never" in ln for ln in lines)
        assert "# TYPE serve_ttft_ms histogram" in lines
        assert 'serve_ttft_ms_bucket{le="1"} 0' in lines
        assert 'serve_ttft_ms_bucket{le="10"} 1' in lines
        assert 'serve_ttft_ms_bucket{le="+Inf"} 1' in lines
        assert "serve_ttft_ms_sum 5" in lines
        assert "serve_ttft_ms_count 1" in lines
        assert text.endswith("\n")

    def test_nonfinite_gauge_spellings(self):
        reg = MetricsRegistry()
        reg.gauge("a").set(float("nan"))
        reg.gauge("b").set(float("inf"))
        text = reg.render_prometheus()
        assert "a NaN" in text and "b +Inf" in text

    def test_empty_registry(self):
        assert MetricsRegistry().render_prometheus() == ""


# ---------------------------------------------------------------------------
# request records + the bounded ring
# ---------------------------------------------------------------------------

def _rec(rid, slot=0, submit=0.0, admit=0.002, first=0.012, last=0.052,
         retire=0.052, generated=5, reason="length", ticks=()):
    r = RequestRecord(request_id=rid, prompt_len=3, submit_t=submit,
                      admit_t=admit, prefill_done_t=first,
                      first_token_t=first, last_token_t=last,
                      retire_t=retire, slot=slot, generated=generated,
                      finish_reason=reason)
    r.decode_ts.extend(ticks)
    return r


class TestRequestRecord:
    def test_derived_latencies(self):
        r = _rec(0)
        assert r.queue_wait_ms == pytest.approx(2.0)
        assert r.ttft_ms == pytest.approx(12.0)
        assert r.e2e_ms == pytest.approx(52.0)
        # 5 tokens, 40 ms from first to last -> 10 ms/token after first
        assert r.tpot_ms == pytest.approx(10.0)

    def test_unstamped_transitions_are_none(self):
        r = RequestRecord(request_id=1, prompt_len=2, submit_t=1.0)
        assert r.queue_wait_ms is None and r.ttft_ms is None
        assert r.tpot_ms is None and r.e2e_ms is None

    def test_single_token_has_no_tpot(self):
        assert _rec(0, generated=1).tpot_ms is None

    def test_to_dict_is_strict_json(self):
        doc = _rec(3, ticks=[0.02, 0.03]).to_dict()
        parsed = json.loads(json.dumps(doc, allow_nan=False))
        assert parsed["request_id"] == 3
        assert parsed["decode_ts"] == [0.02, 0.03]
        assert parsed["tpot_ms"] == pytest.approx(10.0)


class TestRequestTrace:
    def test_overflow_evicts_oldest(self):
        trace = RequestTrace(capacity=3)
        for i in range(5):
            trace.append(_rec(i))
        assert len(trace) == 3
        assert [r.request_id for r in trace.records()] == [2, 3, 4]
        assert [r.request_id for r in trace.last(2)] == [3, 4]
        assert trace.last(0) == []
        assert [r.request_id for r in trace.last(99)] == [2, 3, 4]

    def test_drain_empties_exactly_once(self):
        trace = RequestTrace(capacity=8)
        trace.append(_rec(0))
        assert [r.request_id for r in trace.drain()] == [0]
        assert trace.drain() == [] and len(trace) == 0

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            RequestTrace(capacity=0)

    def test_concurrent_append_drain_and_hook_loses_nothing(self):
        """Mirror of the PR 3 record_span/drain_spans concurrency test:
        producer threads hammer append while a drainer races drain and a
        StepReporter hook (the SLO tracker reading last(n)) runs
        alongside — within capacity, every record comes out exactly
        once."""
        n_producers, per_producer = 4, 200
        trace = RequestTrace(capacity=n_producers * per_producer)
        tracker = SLOTracker([SLOTarget("ttft_ms", 95, 1000.0)],
                             registry=MetricsRegistry(), trace=trace,
                             on_violation="skip")
        reporter = StepReporter([JSONLSink(io.StringIO())],
                                registry=MetricsRegistry(),
                                hooks=[tracker])
        drained, stop = [], threading.Event()

        def produce(k):
            for i in range(per_producer):
                trace.append(_rec(k * per_producer + i, slot=k))

        def drain_loop():
            while not stop.is_set():
                drained.extend(trace.drain())

        def report_loop():
            step = 0
            while not stop.is_set():
                reporter.report(step, metrics={"x": 0.0})
                step += 1

        threads = ([threading.Thread(target=produce, args=(k,))
                    for k in range(n_producers)]
                   + [threading.Thread(target=drain_loop),
                      threading.Thread(target=report_loop)])
        for t in threads:
            t.start()
        for t in threads[:n_producers]:
            t.join()
        stop.set()
        for t in threads[n_producers:]:
            t.join()
        drained.extend(trace.drain())
        ids = sorted(r.request_id for r in drained)
        assert ids == list(range(n_producers * per_producer))


# ---------------------------------------------------------------------------
# Chrome swimlane export
# ---------------------------------------------------------------------------

class TestChromeRequestTrace:
    def test_strict_json_one_lane_per_slot_with_flows(self):
        records = [_rec(0, slot=0), _rec(1, slot=1, submit=0.1, admit=0.11,
                                         first=0.12, last=0.2, retire=0.2),
                   _rec(2, slot=0, submit=0.3, admit=0.31, first=0.32,
                        last=0.4, retire=0.4, ticks=[0.35, 0.4])]
        doc = chrome_request_trace(records, pid=7)
        # strict JSON: round-trips without NaN allowances
        doc2 = json.loads(json.dumps(doc, allow_nan=False))
        events = doc2["traceEvents"]
        lanes = {e["args"]["name"] for e in events
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert lanes == {"queue", "slot 0", "slot 1"}
        spans = [e for e in events if e["ph"] == "X"]
        # one queue span + one slot span per record
        assert sum(1 for e in spans if e["tid"] == 0) == 3
        by_slot = {e["args"]["request_id"]: e["tid"]
                   for e in spans if e["tid"] > 0}
        assert by_slot == {0: 1, 1: 2, 2: 1}
        # the slot span carries the latency vocabulary
        slot_span = next(e for e in spans
                         if e["tid"] > 0 and e["args"]["request_id"] == 0)
        for key in ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms",
                    "prompt_len", "generated", "finish_reason"):
            assert key in slot_span["args"]
        # flow events pair up (start on the queue lane, finish on slot)
        starts = [e for e in events if e["ph"] == "s"]
        finishes = [e for e in events if e["ph"] == "f"]
        assert len(starts) == len(finishes) == 3
        assert {e["id"] for e in starts} == {0, 1, 2}
        assert all(e["tid"] == 0 for e in starts)
        # decode ticks render as instants on the owning slot lane
        ticks = [e for e in events if e["name"] == "tick"]
        assert len(ticks) == 2 and all(e["tid"] == 1 for e in ticks)
        assert all(e["pid"] == 7 for e in events)

    def test_mid_flight_records_render_queue_span_only(self):
        r = RequestRecord(request_id=9, prompt_len=2, submit_t=1.0,
                          admit_t=1.1, slot=0)
        doc = chrome_request_trace([r])
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(spans) == 1 and spans[0]["tid"] == 0
        json.loads(json.dumps(doc, allow_nan=False))

    def test_ticks_off(self):
        doc = chrome_request_trace([_rec(0, ticks=[0.02])], ticks=False)
        assert not [e for e in doc["traceEvents"] if e["name"] == "tick"]


# ---------------------------------------------------------------------------
# the scheduler measures, the engine stays untouched
# ---------------------------------------------------------------------------

def _tiny_model():
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=64)
    model = GPTModel(cfg)
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def model_params():
    return _tiny_model()


@pytest.fixture(scope="module")
def engine(model_params):
    model, params = model_params
    return ServingEngine(model, params, max_seqs=2, max_len=32,
                         prefill_len=8)


class TestSchedulerLifecycle:
    def test_completions_carry_measured_latencies(self, engine):
        reg = MetricsRegistry()
        sched = SlotScheduler(engine, registry=reg)
        # 2 slots, 3 requests: the third queues behind a whole generation
        out = sched.run([Request(prompt=[1 + i, 2], max_new_tokens=4)
                         for i in range(3)])
        assert sorted(out) == [0, 1, 2]
        for c in out.values():
            assert c.queue_wait_ms is not None and c.queue_wait_ms >= 0.0
            assert c.ttft_ms >= c.queue_wait_ms
            assert c.e2e_ms >= c.ttft_ms
            assert c.tpot_ms is not None and c.tpot_ms > 0.0
        # queue wait is MEASURED from submit: the queued request waited
        # out at least one whole earlier generation, the admitted-
        # immediately ones did not
        assert out[2].queue_wait_ms > max(out[0].queue_wait_ms,
                                          out[1].queue_wait_ms)

    def test_single_token_completion_has_no_tpot(self, engine):
        sched = SlotScheduler(engine, registry=MetricsRegistry())
        out = sched.run([Request(prompt=[5], max_new_tokens=1)])
        (c,) = out.values()
        assert c.tpot_ms is None and c.ttft_ms is not None

    def test_latency_histograms_populated(self, engine):
        reg = MetricsRegistry()
        sched = SlotScheduler(engine, registry=reg)
        sched.run([Request(prompt=[1 + i], max_new_tokens=3)
                   for i in range(4)])
        for name in ("serve/queue_wait_ms", "serve/ttft_ms",
                     "serve/tpot_ms", "serve/e2e_ms"):
            h = reg.histogram(name, LATENCY_BUCKETS_MS)
            assert h.count == 4, name
            assert math.isfinite(h.percentile(99))
        # and the whole surface exports as a Prometheus snapshot
        text = reg.render_prometheus()
        assert "serve_ttft_ms_count 4" in text
        assert 'serve_ttft_ms_bucket{le="+Inf"} 4' in text

    def test_trace_ring_and_chrome_export(self, engine):
        trace = RequestTrace(capacity=16)
        sched = SlotScheduler(engine, registry=MetricsRegistry(),
                              trace=trace)
        out = sched.run([Request(prompt=[1 + i, 2], max_new_tokens=3)
                         for i in range(3)])
        assert len(trace) == 3
        for r in trace.records():
            # ticks captured: 3 tokens = 1 prefill sample + 2 decode ticks
            assert len(r.decode_ts) == len(out[r.request_id].tokens) - 1
            assert r.finish_reason == "length" and r.slot in (0, 1)
        doc = trace.chrome_trace()
        doc2 = json.loads(json.dumps(doc, allow_nan=False))
        lanes = {e["args"]["name"] for e in doc2["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert lanes == {"queue", "slot 0", "slot 1"}

    def test_untraced_scheduler_keeps_no_ticks(self, engine):
        sched = SlotScheduler(engine, registry=MetricsRegistry())
        sched.submit(Request(prompt=[1], max_new_tokens=3))
        while sched.pending:
            sched.step()
            for st in sched.active.values():
                assert st.record.decode_ts == []


class TestTracingZeroCost:
    def test_device_programs_byte_identical_and_no_recompiles(
            self, model_params):
        """The acceptance contract: tracing on vs off changes NOTHING on
        the device — the three AOT serving programs are byte-identical,
        and a fully-traced run (ring + SLO tracker) stays flat under the
        recompile guard (PR 11), the way PR 1/PR 3 assert their
        zero-cost modes."""
        model, params = model_params

        def build():
            return ServingEngine(model, params, max_seqs=2, max_len=16,
                                 prefill_len=4)

        eng_off, eng_on = build(), build()
        reqs = [Request(prompt=[1 + i, 2], max_new_tokens=3)
                for i in range(3)]
        sched_off = SlotScheduler(eng_off, registry=MetricsRegistry())
        reg = MetricsRegistry()
        trace = RequestTrace(capacity=8)
        tracker = SLOTracker([SLOTarget("ttft_ms", 95, 5000.0)],
                             registry=reg, trace=trace,
                             on_violation="skip")
        sched_on = SlotScheduler(eng_on, registry=reg, trace=trace,
                                 slo=tracker)
        # no_recompile=True wraps each loop in recompile_guard — a
        # tracing-induced compile or transfer-triggering retrace raises
        out_off = sched_off.run(reqs, no_recompile=True)
        out_on = sched_on.run([Request(prompt=list(r.prompt),
                                       max_new_tokens=r.max_new_tokens)
                               for r in reqs], no_recompile=True)
        # same programs, byte for byte
        for a, b in ((eng_off.prefill_compiled, eng_on.prefill_compiled),
                     (eng_off.decode_compiled, eng_on.decode_compiled),
                     (eng_off.release_compiled, eng_on.release_compiled)):
            assert a.as_text() == b.as_text()
        # and identical greedy token streams — tracing observed, never
        # perturbed
        for rid in out_off:
            assert out_off[rid].tokens == out_on[rid].tokens


# ---------------------------------------------------------------------------
# SLO targets, goodput, burn rate, flight recorder
# ---------------------------------------------------------------------------

class TestSLOTarget:
    def test_validation(self):
        with pytest.raises(ValueError, match="metric"):
            SLOTarget("latency", 95, 100.0)
        with pytest.raises(ValueError, match="quantile"):
            SLOTarget("ttft_ms", 100.0, 100.0)
        with pytest.raises(ValueError, match="threshold"):
            SLOTarget("ttft_ms", 95, 0.0)

    def test_describe_and_budget(self):
        t = SLOTarget("ttft_ms", 95, 200.0)
        assert t.describe() == "ttft_ms p95 <= 200ms"
        assert t.error_budget == pytest.approx(0.05)


def _tracker(targets, trace=None, **kw):
    return SLOTracker(targets, registry=MetricsRegistry(), trace=trace,
                      **kw)


class TestSLOTracker:
    def test_goodput_counts_requests_meeting_all_targets(self):
        tr = _tracker([SLOTarget("ttft_ms", 95, 15.0),
                       SLOTarget("tpot_ms", 99, 12.0)],
                      on_violation="skip")
        assert math.isnan(tr.goodput())
        # rec: ttft 12ms tpot 10ms -> good; push 8 good + 2 bad-ttft
        for i in range(8):
            tr.observe(_rec(i))
        for i in range(2):
            tr.observe(_rec(10 + i, first=0.020, last=0.060, retire=0.060))
        assert tr.goodput() == pytest.approx(0.8)
        reg = tr._reg
        snap = reg.snapshot()
        assert snap["slo/goodput"] == pytest.approx(0.8)
        assert snap["slo/window_requests"] == 10.0

    def test_burn_rate_is_violation_fraction_over_budget(self):
        target = SLOTarget("ttft_ms", 90, 15.0)  # budget 10%
        tr = _tracker([target], on_violation="skip")
        for i in range(9):
            tr.observe(_rec(i))                      # ttft 12 -> ok
        tr.observe(_rec(9, first=0.020, retire=0.060))  # ttft 20 -> over
        # 10% violating / 10% budget = burning exactly the budget
        assert tr.burn_rate(target) == pytest.approx(1.0)
        assert tr._reg.snapshot()["slo/burn_rate"] == pytest.approx(1.0)

    def test_window_percentile_matches_numpy(self):
        target = SLOTarget("e2e_ms", 95, 1000.0)
        tr = _tracker([target], on_violation="skip")
        vals = np.random.RandomState(0).uniform(10, 90, 40)
        for i, v in enumerate(vals):
            tr.observe(_rec(i, retire=v / 1e3))
        assert tr.window_percentile(target) == pytest.approx(
            float(np.percentile(vals, 95)))

    def test_undefined_metric_neither_helps_nor_hurts(self):
        tr = _tracker([SLOTarget("tpot_ms", 99, 1.0)], on_violation="skip")
        tr.observe(_rec(0, generated=1))  # no tpot on 1-token requests
        assert tr.goodput() == 1.0  # vacuously good
        assert math.isnan(tr.burn_rate(tr.targets[0]))
        assert not tr.violating_targets()

    def test_rolling_window_evicts(self):
        tr = _tracker([SLOTarget("ttft_ms", 95, 15.0)], window=4,
                      on_violation="skip")
        for i in range(4):  # all bad
            tr.observe(_rec(i, first=0.020, retire=0.060))
        assert tr.goodput() == 0.0
        for i in range(4):  # window rolls over to all good
            tr.observe(_rec(10 + i))
        assert tr.goodput() == 1.0

    def test_forced_violation_writes_flight_recorder_dump(self, tmp_path):
        """The acceptance test: a violating window + a report hook call
        produce a strict-JSON CrashDump carrying the last-N request
        records from the ring."""
        trace = RequestTrace(capacity=16)
        tr = _tracker([SLOTarget("ttft_ms", 50, 1.0)], trace=trace,
                      on_violation="dump", dump_dir=str(tmp_path),
                      flight_n=3)
        for i in range(5):
            rec = _rec(i, slot=i % 2)
            trace.append(rec)
            tr.observe(rec)  # ttft 12ms >> 1ms: violating
        assert tr.violating_targets() == list(tr.targets)
        assert tr._reg.snapshot()["slo/violating"] == 1.0
        tr(step=42, payload={"serve/tokens_per_sec": 5.0})
        (path,) = tr.dumps
        assert path.endswith("slo_dump_step00000042.json")
        doc = json.loads(open(path).read())  # strict JSON
        assert [r["request_id"] for r in doc["requests"]] == [2, 3, 4]
        assert doc["requests"][0]["ttft_ms"] == pytest.approx(12.0)
        assert doc["config"]["targets"] == ["ttft_ms p50 <= 1ms"]
        assert doc["metrics"]["serve/tokens_per_sec"] == 5.0
        assert tr._reg.snapshot()["slo/violations"] == 1.0

    def test_raise_policy(self, tmp_path):
        tr = _tracker([SLOTarget("ttft_ms", 50, 1.0)],
                      on_violation="raise", dump_dir=str(tmp_path))
        tr.observe(_rec(0))
        with pytest.raises(SLOViolationError, match="ttft_ms p50") as ei:
            tr(step=1, payload={})
        assert ei.value.dump_path and ei.value.dump.requests == []

    def test_skip_policy_never_dumps(self, tmp_path):
        tr = _tracker([SLOTarget("ttft_ms", 50, 1.0)],
                      on_violation="skip", dump_dir=str(tmp_path))
        tr.observe(_rec(0))
        tr(step=1, payload={})
        assert tr.dumps == [] and not list(tmp_path.iterdir())

    def test_consecutive_streak_and_reset(self, tmp_path):
        tr = _tracker([SLOTarget("ttft_ms", 50, 15.0)], window=2,
                      on_violation="dump", dump_dir=str(tmp_path),
                      consecutive=2)
        tr.observe(_rec(0, first=0.020, retire=0.060))  # violating window
        tr.observe(_rec(1, first=0.020, retire=0.060))
        tr(step=1, payload={})
        assert tr.dumps == []  # streak 1 < 2
        tr.observe(_rec(2))  # clean window now
        tr.observe(_rec(3))
        tr(step=2, payload={})
        assert tr.streak == 0 and tr.dumps == []  # reset, no dump
        tr.observe(_rec(4, first=0.020, retire=0.060))
        tr.observe(_rec(5, first=0.020, retire=0.060))
        tr(step=3, payload={})
        assert tr.dumps == []  # fresh streak: 1 < 2 again
        tr(step=4, payload={})  # 2nd consecutive violating report: fires
        assert [p.split("step")[-1] for p in tr.dumps] == ["00000004.json"]

    def test_validation(self):
        with pytest.raises(ValueError, match="at least one"):
            _tracker([])
        with pytest.raises(ValueError, match="on_violation"):
            _tracker([SLOTarget("ttft_ms", 95, 1.0)], on_violation="page")
        with pytest.raises(ValueError, match="window"):
            _tracker([SLOTarget("ttft_ms", 95, 1.0)], window=0)


class TestReporterIntegration:
    def test_slo_hook_through_step_reporter(self, engine, tmp_path):
        """The full wiring, HealthMonitor-style: scheduler feeds tracker,
        StepReporter(hooks=[tracker]) emits the slo/* gauges to sinks
        and the violating report writes the flight dump."""
        buf = io.StringIO()
        reg = MetricsRegistry()
        trace = RequestTrace(capacity=16)
        tracker = SLOTracker(
            [SLOTarget("ttft_ms", 50, 1e-6)],  # impossible: must violate
            registry=reg, trace=trace, on_violation="dump",
            dump_dir=str(tmp_path), flight_n=8)
        sched = SlotScheduler(engine, registry=reg, trace=trace,
                              slo=tracker)
        with StepReporter([JSONLSink(buf)], registry=reg,
                          hooks=[tracker]) as reporter:
            sched.run([Request(prompt=[1 + i], max_new_tokens=2)
                       for i in range(3)])
            reporter.report(0)
        (line,) = [ln for ln in buf.getvalue().splitlines() if ln]
        payload = json.loads(line)["metrics"]
        assert payload["slo/goodput"] == 0.0
        assert payload["slo/violating"] == 1.0
        assert payload["serve/ttft_ms_count"] == 3.0
        (path,) = tracker.dumps
        doc = json.loads(open(path).read())
        assert len(doc["requests"]) == 3
        assert {r["finish_reason"] for r in doc["requests"]} == {"length"}
