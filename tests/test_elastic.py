"""Elastic runtime tests: async checkpointing, resumable data, fault
injection, and the preemption-safe run loop.

The heavy end-to-end GPT subprocess legs live in
``tests/test_elastic_resume.py``; here a GPTHybridTrainer-shaped
:class:`ToyTrainer` (bf16 params + a typed PRNG key in the state, so the
fp32-on-disk widening and RNG resume paths are exercised) keeps the loop
semantics fast to test in-process.
"""

import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.checkpoint import (all_steps, restore_checkpoint,
                                 save_checkpoint, torn_steps)
from apex_tpu.elastic import (AsyncCheckpointer, ElasticRunner, FaultPlan,
                              PrefetchingIterator, ShardedIndexIterator,
                              host_snapshot, owned_copy, snapshot_nbytes,
                              token_batch_fetcher)
from apex_tpu.observability.registry import MetricsRegistry


def _bits(tree):
    out = []
    for x in jax.tree_util.tree_leaves(host_snapshot(tree)):
        arr = np.asarray(x)
        out.append((str(arr.dtype), arr.tobytes()))
    return out


# ---------------------------------------------------------------------------
# a GPTHybridTrainer-shaped toy: init_state(key) -> state tuple,
# jit_train_step() -> fn(*state, *batch) -> (loss, *state)
# ---------------------------------------------------------------------------

class ToyTrainer:
    def init_state(self, key):
        w = jax.random.normal(key, (8,), jnp.float32).astype(jnp.bfloat16)
        return (w, jnp.zeros((), jnp.float32), jax.random.key(7))

    def jit_train_step(self):
        @jax.jit
        def step(w, opt, rng, x):
            rng, sub = jax.random.split(rng)
            w32 = w.astype(jnp.float32)
            loss = jnp.mean((w32 - x) ** 2)
            noise = 1e-3 * jax.random.normal(sub, w.shape, jnp.float32)
            new_w = (w32 - 0.1 * (w32 - x) + noise).astype(jnp.bfloat16)
            return loss, new_w, opt + 1.0, rng

        return step


def _toy_data(seed=11):
    data = np.random.RandomState(3).randn(64, 8).astype(np.float32)
    sampler = ShardedIndexIterator(64, 4, seed=seed)
    return PrefetchingIterator(
        sampler, lambda idx: (np.take(data, idx, axis=0).mean(0),),
        depth=2)


def _run(tmpdir, total, *, fault_plan=None, fp32_on_disk=True,
         save_interval=1, keep_last=4):
    """One ElasticRunner.fit on a fresh ToyTrainer + data iterator."""
    it = _toy_data()
    runner = ElasticRunner(
        ToyTrainer(), it, str(tmpdir), save_interval=save_interval,
        keep_last=keep_last, fp32_on_disk=fp32_on_disk,
        fault_plan=fault_plan, exit_on_preempt=False,
        registry=MetricsRegistry())
    res = runner.fit(total, key=jax.random.PRNGKey(0))
    return res, it


# ---------------------------------------------------------------------------
# ShardedIndexIterator / PrefetchingIterator
# ---------------------------------------------------------------------------

class TestShardedIndexIterator:
    def test_deterministic_and_random_access(self):
        a = ShardedIndexIterator(100, 10, seed=5)
        b = ShardedIndexIterator(100, 10, seed=5)
        seq = [next(a) for _ in range(12)]
        for k, rows in enumerate(seq):
            np.testing.assert_array_equal(rows, b.batch_indices(k))

    def test_epochs_reshuffle_without_wallclock(self):
        it = ShardedIndexIterator(20, 10, seed=0)  # 2 batches/epoch
        e0 = np.concatenate([next(it), next(it)])
        e1 = np.concatenate([next(it), next(it)])
        assert sorted(e0) == sorted(e1) == list(range(20))
        assert not np.array_equal(e0, e1)  # epoch key mixed into the perm

    def test_host_shards_partition_the_global_batch(self):
        full = ShardedIndexIterator(64, 8, seed=2).batch_indices(3)
        parts = [ShardedIndexIterator(64, 8, seed=2, host_id=h,
                                      num_hosts=2).batch_indices(3)
                 for h in range(2)]
        np.testing.assert_array_equal(np.concatenate(parts), full)

    def test_cursor_seek_matches_straight_run(self):
        a = ShardedIndexIterator(50, 5, seed=9)
        ref = [next(a) for _ in range(8)]
        b = ShardedIndexIterator(50, 5, seed=9)
        b.load_state_dict({"consumed": 6, "seed": 9})
        np.testing.assert_array_equal(next(b), ref[6])
        np.testing.assert_array_equal(next(b), ref[7])

    def test_seed_mismatch_is_loud(self):
        it = ShardedIndexIterator(50, 5, seed=9)
        with pytest.raises(ValueError, match="seed"):
            it.load_state_dict({"consumed": 2, "seed": 10})

    def test_validation(self):
        with pytest.raises(ValueError):
            ShardedIndexIterator(4, 8, seed=0)
        with pytest.raises(ValueError):
            ShardedIndexIterator(64, 9, seed=0, num_hosts=2)


class TestPrefetchingIterator:
    def test_matches_unprefetched_stream(self):
        data = np.random.RandomState(0).randint(0, 32, (64, 9))
        fetch = token_batch_fetcher(data, 2, 2, 8)
        pf = PrefetchingIterator(ShardedIndexIterator(64, 4, seed=1),
                                 fetch, depth=3)
        plain = ShardedIndexIterator(64, 4, seed=1)
        for _ in range(6):
            got = next(pf)
            ref = fetch(next(plain))
            np.testing.assert_array_equal(np.asarray(got[0]), ref[0])
            np.testing.assert_array_equal(np.asarray(got[1]), ref[1])

    def test_cursor_counts_consumed_not_fetched(self):
        data = np.random.RandomState(0).randn(32, 8).astype(np.float32)
        pf = PrefetchingIterator(ShardedIndexIterator(32, 4, seed=1),
                                 lambda idx: (np.take(data, idx, 0),),
                                 depth=3)
        next(pf), next(pf)
        state = pf.state_dict()
        assert state["consumed"] == 2
        # the sampler ran ahead by the prefetch depth
        assert pf.sampler.consumed > 2
        # a fresh pipeline seeked to the cursor yields batch 2 next
        pf2 = PrefetchingIterator(ShardedIndexIterator(32, 4, seed=1),
                                  lambda idx: (np.take(data, idx, 0),),
                                  depth=3)
        pf2.load_state_dict(state)
        ref = PrefetchingIterator(ShardedIndexIterator(32, 4, seed=1),
                                  lambda idx: (np.take(data, idx, 0),),
                                  depth=1)
        next(ref), next(ref)
        np.testing.assert_array_equal(np.asarray(next(pf2)[0]),
                                      np.asarray(next(ref)[0]))


# ---------------------------------------------------------------------------
# AsyncCheckpointer
# ---------------------------------------------------------------------------

class TestAsyncCheckpointer:
    def test_basic_roundtrip_and_metrics(self, tmp_path):
        reg = MetricsRegistry()
        state = {"w": jnp.arange(8, dtype=jnp.float32),
                 "k": jax.random.key(3)}
        with AsyncCheckpointer(str(tmp_path), keep_last=2,
                               registry=reg) as ck:
            for s in (1, 2, 3):
                ck.save(state, s, host_state={"step": s})
        assert all_steps(str(tmp_path)) == [2, 3]  # keep_last GC'd step 1
        restored, host = restore_checkpoint(str(tmp_path), state)
        assert host["step"] == 3
        assert _bits(restored) == _bits(state)
        snap = reg.snapshot()
        assert snap["ckpt/saves"] == 3
        assert snap["ckpt/inflight"] == 0
        assert snap["ckpt/bytes"] == 3 * snapshot_nbytes(
            host_snapshot(state))
        assert snap["ckpt/save_ms_count"] == 3

    def test_snapshot_owns_its_memory(self):
        # CPU device_get can alias the device buffer; the snapshot must
        # not (the donated step reuses those bytes — see host_snapshot)
        x = jnp.arange(16, dtype=jnp.float32)
        snap = host_snapshot({"x": x})["x"]
        assert snap.flags.owndata
        assert not np.shares_memory(snap, np.asarray(x))

    def test_owned_copy_preserves_values_and_key_type(self):
        state = {"w": jnp.arange(4, dtype=jnp.bfloat16),
                 "k": jax.random.key(5)}
        copied = owned_copy(state)
        assert _bits(copied) == _bits(state)
        assert jnp.issubdtype(copied["k"].dtype, jax.dtypes.prng_key)

    def test_transient_oserror_retried_with_backoff(self, tmp_path):
        reg = MetricsRegistry()
        plan = FaultPlan(save_errors={5: 2})
        ck = AsyncCheckpointer(str(tmp_path), registry=reg,
                               fault_hook=plan.on_save_attempt,
                               backoff_s=0.001)
        ck.save({"w": jnp.zeros(3)}, 5, block=True)
        assert all_steps(str(tmp_path)) == [5]
        assert reg.snapshot()["ckpt/retries"] == 2

    def test_exhausted_retries_raise_on_drain_not_silently(self, tmp_path):
        plan = FaultPlan(save_errors={7: 99})
        ck = AsyncCheckpointer(str(tmp_path), registry=MetricsRegistry(),
                               fault_hook=plan.on_save_attempt,
                               max_retries=1, backoff_s=0.001)
        ck.save({"w": jnp.zeros(3)}, 7)
        with pytest.raises(OSError, match="after 2 attempt"):
            ck.drain()
        ck.drain()  # error is consumed once, not resurfaced forever
        assert all_steps(str(tmp_path)) == []

    def test_off_critical_path(self, tmp_path):
        """The acceptance-criterion shape, asserted STRUCTURALLY: every
        ``ck.save`` must return before its own serialization completes —
        the off-the-critical-path property itself. (The original
        wall-clock form — "the loop beats n*(step+serialize)" — flaked
        unfixably on slow/noisy 2-core CI hosts where the real orbax
        write outruns any hard-coded step budget; completion-vs-return
        ordering is load-invariant: a synchronous implementation orders
        every completion BEFORE its save() returns, an async one after,
        regardless of how slow the box is.)"""
        serialize_s, n = 0.15, 5
        state = {"w": jnp.arange(4, dtype=jnp.float32)}
        done_at = {}

        def slow_save(directory, state, step, **kw):
            time.sleep(serialize_s)
            out = save_checkpoint(directory, state, step, **kw)
            done_at[step] = time.perf_counter()
            return out

        ck = AsyncCheckpointer(str(tmp_path), registry=MetricsRegistry(),
                               save_fn=slow_save)
        returned_at = {}
        sleep_start = {}
        for k in range(n):
            sleep_start[k] = time.perf_counter()
            time.sleep(0.05)         # the "train step"
            ck.save(state, k)
            returned_at[k] = time.perf_counter()
        ck.drain()
        # every save's serialization finished AFTER its dispatch call
        # had already returned control to the step loop (a synchronous
        # save_fn execution inside save() orders them the other way)
        assert all(done_at[k] > returned_at[k] for k in range(n)), \
            {k: done_at[k] - returned_at[k] for k in range(n)}
        # and the background work genuinely ran INSIDE later steps'
        # compute windows: with a 0.05s step and 0.15s serialization,
        # save k must still be serializing when step k+1 starts (an
        # implementation that paid the serialization anywhere inside
        # the loop's critical path could not produce this ordering for
        # every k; load only pushes completions later, never earlier)
        assert all(done_at[k] > sleep_start[k + 1] for k in range(n - 1)), \
            {k: done_at[k] - sleep_start[k + 1] for k in range(n - 1)}
        assert all_steps(str(tmp_path)) == list(range(n))

    def test_keep_last_never_deletes_uncommitted_dirs(self, tmp_path):
        save_checkpoint(str(tmp_path), {"w": jnp.zeros(2)}, 1)
        torn = tmp_path / "step_00000002"
        torn.mkdir()  # another writer's in-progress dir: no COMMITTED
        for s in (3, 4):
            save_checkpoint(str(tmp_path), {"w": jnp.zeros(2)}, s,
                            keep_last=2)
        assert all_steps(str(tmp_path)) == [3, 4]
        assert torn.is_dir()  # GC must never touch an uncommitted dir
        assert torn_steps(str(tmp_path)) == [2]


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------

class TestFaultPlan:
    def test_sample_is_deterministic_and_json_roundtrips(self):
        a = FaultPlan.sample(17, 10, tear=True)
        b = FaultPlan.sample(17, 10, tear=True)
        assert a == b
        assert FaultPlan.from_json(a.to_json()) == a
        assert 1 <= a.sigterm_at_step < 10

    def test_sample_snaps_error_to_a_real_save_step(self):
        """With save_interval > 1 an error keyed to a never-saved step
        would inject nothing — sample must land on a multiple of the
        interval (or the preemption save itself)."""
        for seed in range(20):
            plan = FaultPlan.sample(seed, 12, save_interval=5)
            (err_step,) = plan.save_errors
            k = plan.sigterm_at_step
            assert err_step == k or (err_step % 5 == 0
                                     and err_step <= k), plan

    def test_before_step_delivers_real_sigterm(self):
        hits = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
        try:
            plan = FaultPlan(sigterm_at_step=3)
            plan.before_step(2)
            assert hits == []
            plan.before_step(3)
            assert hits == [signal.SIGTERM]
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_tear_after_save(self, tmp_path):
        plan = FaultPlan(tear_after_step=2)
        path = save_checkpoint(str(tmp_path), {"w": jnp.zeros(2)}, 2)
        plan.after_save(2, path)
        assert all_steps(str(tmp_path)) == []
        assert torn_steps(str(tmp_path)) == [2]


# ---------------------------------------------------------------------------
# ElasticRunner (in-process, ToyTrainer)
# ---------------------------------------------------------------------------

class TestElasticRunner:
    @pytest.mark.parametrize("fp32_on_disk", [True, False])
    def test_preempt_resume_bitwise(self, tmp_path, fp32_on_disk):
        """3 steps + fault-plan preempt + restore + 3 steps == 6 straight
        steps, bitwise — bf16 params through the fp32-on-disk widening,
        optimizer scalar, typed RNG key, and the data cursor."""
        ref, ref_it = _run(tmp_path / "ref", 6,
                           fp32_on_disk=fp32_on_disk)
        assert not ref.preempted

        d = tmp_path / "run"
        first, _ = _run(d, 6, fp32_on_disk=fp32_on_disk,
                        fault_plan=FaultPlan(sigterm_at_step=3))
        assert first.preempted and first.step == 3
        second, it2 = _run(d, 6, fp32_on_disk=fp32_on_disk)
        assert not second.preempted
        assert second.restored_from == 3 and second.step == 6
        assert _bits(second.state) == _bits(ref.state)
        assert it2.consumed == ref_it.consumed == 6

    def test_torn_final_checkpoint_falls_back_loudly(self, tmp_path):
        """A preemption save whose COMMITTED marker is lost (writer died
        between array write and commit) must not poison the run: restore
        warns, falls back to the previous COMMITTED step, and the rerun
        stays bitwise."""
        ref, _ = _run(tmp_path / "ref", 5)
        d = tmp_path / "run"
        plan = FaultPlan(sigterm_at_step=3, save_errors={2: 1},
                         tear_after_step=3)
        first, _ = _run(d, 5, fault_plan=plan)
        assert first.preempted and first.step == 3
        assert torn_steps(str(d)) == [3]
        with pytest.warns(UserWarning, match="torn"):
            second, _ = _run(d, 5)
        assert second.restored_from == 2  # fell back past the torn step 3
        assert _bits(second.state) == _bits(ref.state)

    def test_preempt_drains_inflight_save(self, tmp_path):
        """A save in flight when the preemption lands is drained, not
        corrupted: every dir with a COMMITTED marker restores."""
        plan = FaultPlan(sigterm_at_step=3, slow_save_s=0.1)
        res, _ = _run(tmp_path, 6, fault_plan=plan)
        assert res.preempted
        target = jax.tree_util.tree_map(lambda x: x, res.state)
        for s in all_steps(str(tmp_path)):
            restored, host = restore_checkpoint(str(tmp_path), target,
                                                step=s)
            assert host["step"] == s
        # the preemption-time state itself was committed
        assert all_steps(str(tmp_path))[-1] == 3

    def test_env_var_termination_is_a_preemption(self, tmp_path,
                                                 monkeypatch):
        monkeypatch.delenv("APEX_TPU_TERMINATE", raising=False)
        calls = {"n": 0}

        def trip_after_two():
            calls["n"] += 1
            if calls["n"] == 3:
                monkeypatch.setenv("APEX_TPU_TERMINATE", "now")

        it = _toy_data()
        runner = ElasticRunner(
            ToyTrainer(), it, str(tmp_path), save_interval=1,
            exit_on_preempt=False, registry=MetricsRegistry(),
            on_step=lambda k, loss: trip_after_two())
        res = runner.fit(10, key=jax.random.PRNGKey(0))
        assert res.preempted and res.step == 3

    def test_restart_after_completion_never_rewrites_the_checkpoint(
            self, tmp_path):
        """A fit that restores at N and runs zero further steps must NOT
        re-save step N: save_checkpoint rmtree's the committed dir before
        rewriting, and a kill in that window would destroy the newest
        (with keep_last=1, the ONLY) checkpoint."""
        reg = MetricsRegistry()
        it = _toy_data()
        ElasticRunner(ToyTrainer(), it, str(tmp_path), save_interval=10,
                      keep_last=1, exit_on_preempt=False,
                      registry=reg).fit(3, key=jax.random.PRNGKey(0))
        marker = tmp_path / "step_00000003" / "COMMITTED"
        mtime = marker.stat().st_mtime_ns
        saves = reg.snapshot()["ckpt/saves"]
        res = ElasticRunner(ToyTrainer(), _toy_data(), str(tmp_path),
                            save_interval=10, keep_last=1,
                            exit_on_preempt=False, registry=reg).fit(
                                3, key=jax.random.PRNGKey(0))
        assert res.restored_from == 3 and res.step == 3
        assert reg.snapshot()["ckpt/saves"] == saves  # no rewrite
        assert marker.stat().st_mtime_ns == mtime

    def test_completed_run_reports_metrics(self, tmp_path):
        reg = MetricsRegistry()
        it = _toy_data()
        runner = ElasticRunner(ToyTrainer(), it, str(tmp_path),
                               save_interval=2, keep_last=2,
                               exit_on_preempt=False, registry=reg)
        res = runner.fit(4, key=jax.random.PRNGKey(0))
        assert not res.preempted and res.loss is not None
        snap = reg.snapshot()
        assert snap["ckpt/saves"] >= 2
        # resume metrics appear once a restore happens
        runner2 = ElasticRunner(ToyTrainer(), _toy_data(), str(tmp_path),
                                save_interval=2, exit_on_preempt=False,
                                registry=reg)
        runner2.fit(4, key=jax.random.PRNGKey(0))
        snap = reg.snapshot()
        assert snap["resume/resumes"] == 1
        assert snap["resume/restored_step"] == 4

    def test_save_interval_validation(self, tmp_path):
        with pytest.raises(ValueError):
            ElasticRunner(ToyTrainer(), _toy_data(), str(tmp_path),
                          save_interval=0)


# ---------------------------------------------------------------------------
# cross-config restore guard (PR 4 bucket_stamp at the jit boundary)
# ---------------------------------------------------------------------------

class TestCrossConfigRestoreGuard:
    def test_zero_checkpoint_under_other_bucket_bytes_raises(self,
                                                             tmp_path):
        """A ZeRO-1 checkpoint saved under ``ddp_bucket_bytes=A`` restored
        into a trainer configured with ``B != A`` must raise LOUDLY at the
        ``jit_train_step`` boundary — the flat optimizer shards are
        bucket-major, so stepping them under the wrong grid would silently
        permute every master/moment element."""
        from apex_tpu.config import (BatchConfig, ModelConfig,
                                     OptimizerConfig, ParallelConfig,
                                     TrainConfig)
        from apex_tpu.training import GPTHybridTrainer
        from apex_tpu.transformer import parallel_state

        M, mb, dp, seq, vocab = 2, 1, 4, 8, 32

        def make_cfg(bucket_bytes):
            return TrainConfig(
                model=ModelConfig(name="gpt", vocab_size=vocab,
                                  hidden_size=16, num_layers=1,
                                  num_attention_heads=2,
                                  max_position_embeddings=seq),
                parallel=ParallelConfig(tensor_model_parallel_size=1,
                                        pipeline_model_parallel_size=1),
                batch=BatchConfig(global_batch_size=M * mb * dp,
                                  micro_batch_size=mb),
                optimizer=OptimizerConfig(name="adam", lr=1e-2,
                                          weight_decay=0.0, zero=1),
                opt_level="O0", ddp_bucket_bytes=bucket_bytes)

        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, vocab, (M, dp * mb, seq)))
        targets = jnp.asarray(rng.randint(0, vocab, (M, dp * mb, seq)))

        cfg_a = make_cfg(1024)
        mesh_a = cfg_a.initialize_mesh(devices=jax.devices()[:dp])
        try:
            trainer_a = GPTHybridTrainer(cfg_a, mesh_a)
            state_a = trainer_a.init_state(jax.random.PRNGKey(0))
            save_checkpoint(str(tmp_path), tuple(state_a), step=1)
        finally:
            parallel_state.destroy_model_parallel()

        cfg_b = make_cfg(2048)
        mesh_b = cfg_b.initialize_mesh(devices=jax.devices()[:dp])
        try:
            trainer_b = GPTHybridTrainer(cfg_b, mesh_b)
            state_b = trainer_b.init_state(jax.random.PRNGKey(0))
            restored, _ = restore_checkpoint(str(tmp_path),
                                             tuple(state_b))
            with pytest.raises(ValueError, match="bucket_bytes"):
                trainer_b.jit_train_step()(*restored, tokens, targets)
        finally:
            parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# the zero-recompile budget on the production loop (analysis engine, PR 11)
# ---------------------------------------------------------------------------

class TestFitNoRecompile:
    def test_steady_loop_passes_the_guard(self, tmp_path):
        """fit(no_recompile=True): first step + first save are warmup;
        the steady-state loop must not move the compile-storm counters."""
        runner = ElasticRunner(ToyTrainer(), _toy_data(), str(tmp_path),
                               save_interval=2, keep_last=2,
                               exit_on_preempt=False,
                               registry=MetricsRegistry())
        res = runner.fit(6, key=jax.random.PRNGKey(0),
                         no_recompile=True)
        assert not res.preempted and res.step == 6

    def test_retracing_step_trips_the_guard(self, tmp_path):
        """A trainer whose step retraces every call (the storm class the
        guard exists for) fails fit(no_recompile=True) loudly."""
        from apex_tpu.analysis import AnalysisError

        class RetracingTrainer(ToyTrainer):
            def jit_train_step(self):
                def step(w, opt, rng, x):
                    # a FRESH jit per dispatch: compiles every step
                    return jax.jit(ToyTrainer.jit_train_step(self))(
                        w, opt, rng, x)
                return step

        runner = ElasticRunner(RetracingTrainer(), _toy_data(),
                               str(tmp_path), save_interval=2,
                               exit_on_preempt=False,
                               registry=MetricsRegistry())
        with pytest.raises(AnalysisError, match="compile-storm"):
            runner.fit(6, key=jax.random.PRNGKey(0), no_recompile=True)
