"""Compound fastpath preset + roofline bucket autotuning tests.

Covers this PR's contracts on the 8-virtual-CPU-device mesh:

- ``TrainConfig.fastpath()``: the declarative compound preset (ZeRO-1 +
  auto-bucketed DP + selective remat, SP/tp_comm_overlap only where the
  mesh/jax can carry them), its drift-proof equality with bench.py's
  declarative ``BENCH_TRAIN_CONFIGS`` record, and its loud refusal on
  non-ZeRO-capable optimizers;
- ``pyprof.tune_bucket_bytes`` / ``bucket_wire_ms``: monotone wire-time
  model, the smallest-fully-hideable decision rule, deterministic picks,
  and the LOUD fallback to ``DEFAULT_BUCKET_BYTES`` on unpriceable
  programs;
- ``ddp_bucket_bytes="auto"`` through ``GPTHybridTrainer``: resolved at
  construction, deterministically, stored back into the trainer's config
  (the ZeRO ``bucket_stamp`` layout contract) and surfaced as the
  ``ddp/auto_bucket_bytes`` gauge;
- the compound structural assertion (satellite): the fastpath trainer
  step's jaxpr holds exactly B data-axis reduce-scatters + B gathers,
  zero full-tree psums of the flat gradient, NO materialized padded flat
  vector (the backward-interleave contract), and zero fused
  all_gather/reduce_scatter inside the wired TP layers — the per-feature
  assertions from PRs 2/4, asserted together for the first time;
- fastpath numerics: the compound configuration reproduces the plain
  trainer's loss trajectory (the overlap machinery is a schedule, not a
  numerics change).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jaxpr_utils import (count_eqns, eqn_axes, flat_materializations,
                          iter_eqns)
from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                             ParallelConfig, TrainConfig)
from apex_tpu.observability.costs import DeviceSpec
from apex_tpu.parallel.distributed import DEFAULT_BUCKET_BYTES
from apex_tpu.pyprof import bucket_wire_ms, tune_bucket_bytes
from apex_tpu.pyprof.tune import DEFAULT_CANDIDATES
from apex_tpu.utils.compat import HAS_VMA

SPEC = DeviceSpec("test", 200e12, 800.0, 50.0)


# ---------------------------------------------------------------------------
# the preset
# ---------------------------------------------------------------------------

def _cfg(tp=1, pp=1, dp=4, opt="adam", **model_kw):
    M, mb, seq = 2, 2, 8
    return TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=64, hidden_size=32,
                          num_layers=2, num_attention_heads=4,
                          max_position_embeddings=seq, **model_kw),
        parallel=ParallelConfig(tensor_model_parallel_size=tp,
                                pipeline_model_parallel_size=pp),
        batch=BatchConfig(global_batch_size=M * mb * dp,
                          micro_batch_size=mb),
        optimizer=OptimizerConfig(name=opt, lr=1e-2, weight_decay=0.0),
        opt_level="O0")


def test_fastpath_preset_fields():
    fast = _cfg().fastpath()
    assert fast.optimizer.zero == 1
    assert fast.ddp_bucket_bytes == "auto"
    assert fast.model.remat_policy == "selective"
    # tp=1: no SP to turn on, on any jax
    assert not fast.model.sequence_parallel
    assert not fast.model.tp_comm_overlap
    # bucket grid overridable (the elastic child / dryrun pin it)
    assert _cfg().fastpath(bucket_bytes=4096).ddp_bucket_bytes == 4096
    # explicit receiver settings are kept, not clobbered — including a
    # hand-tuned bucket grid (a checkpoint-layout property) and the
    # deprecated remat=True spelling (means "full", not "selective")
    assert _cfg(remat_policy="full").fastpath().model.remat_policy == "full"
    import dataclasses
    pinned = dataclasses.replace(_cfg(), ddp_bucket_bytes=8 << 20)
    assert pinned.fastpath().ddp_bucket_bytes == 8 << 20
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("ignore", DeprecationWarning)
        assert _cfg(remat=True).fastpath().model.remat_policy == "full"


def test_fastpath_sp_gating_follows_capability():
    fast = _cfg(tp=2, pp=1, dp=2).fastpath()
    assert fast.model.sequence_parallel == HAS_VMA
    assert fast.model.tp_comm_overlap == HAS_VMA
    # pp>1 never carries SP regardless of jax line
    fast_pp = _cfg(tp=2, pp=2, dp=1).fastpath()
    assert not fast_pp.model.sequence_parallel


def test_fastpath_rejects_non_zero_optimizer():
    with pytest.raises(ValueError, match="ZeRO-capable"):
        _cfg(opt="sgd").fastpath()


def test_fastpath_matches_bench_declarative_record():
    """bench.py's BENCH_TRAIN_CONFIGS['gpt_fast'] is the declarative
    record of the preset — it must apply to the same config fastpath()
    produces (capability-gated SP fields aside), so the table cannot
    drift from the preset."""
    import bench

    base = _cfg()
    from_table = bench._train_config_from_spec(
        {"model": {"vocab_size": 64, "hidden_size": 32, "num_layers": 2,
                   "num_attention_heads": 4, "max_position_embeddings": 8},
         "optimizer": {"name": "adam", "lr": 1e-2, "weight_decay": 0.0},
         "opt_level": "O0"},
        bench.BENCH_TRAIN_CONFIGS["gpt_fast"],
        parallel={"tensor_model_parallel_size": 1},
        batch={"global_batch_size": 16, "micro_batch_size": 2})
    fast = base.fastpath()
    assert from_table.optimizer.zero == fast.optimizer.zero == 1
    assert from_table.ddp_bucket_bytes == fast.ddp_bucket_bytes == "auto"
    assert from_table.model.remat_policy == fast.model.remat_policy \
        == "selective"


# ---------------------------------------------------------------------------
# the tuner
# ---------------------------------------------------------------------------

def test_bucket_wire_ms_monotone():
    """The wire-time model: strictly increasing in bucket bytes (at fixed
    ring) and non-decreasing in ring size; zero wire at axis_size=1."""
    sizes = [1 << s for s in range(16, 27)]
    walls = [bucket_wire_ms(c, 4, SPEC) for c in sizes]
    assert all(b > a for a, b in zip(walls, walls[1:])), walls
    rings = [bucket_wire_ms(1 << 22, n, SPEC) for n in (2, 4, 8, 16)]
    assert all(b >= a for a, b in zip(rings, rings[1:])), rings
    assert bucket_wire_ms(1 << 22, 1, SPEC) == 0.0
    with pytest.raises(ValueError, match="positive"):
        bucket_wire_ms(0, 4, SPEC)


def test_tune_picks_smallest_fully_hideable():
    grad_bytes = 64 << 20
    picked = tune_bucket_bytes(grad_bytes=grad_bytes, axis_size=4,
                               spec=SPEC, hide_ms=50.0)
    assert picked in DEFAULT_CANDIDATES
    B = -(-grad_bytes // picked)
    assert bucket_wire_ms(picked, 4, SPEC) <= 50.0 / B
    # every smaller candidate was NOT fully hideable
    for c in DEFAULT_CANDIDATES:
        if c >= picked:
            break
        assert bucket_wire_ms(c, 4, SPEC) > 50.0 / (-(-grad_bytes // c))
    # a huge hide window: the smallest candidate wins outright (most
    # overlap edges at zero exposed wire)
    assert tune_bucket_bytes(grad_bytes=grad_bytes, axis_size=4,
                             spec=SPEC, hide_ms=1e6) \
        == min(DEFAULT_CANDIDATES)


def test_tune_is_deterministic_and_starved_pick_is_least_exposed():
    kw = dict(grad_bytes=256 << 20, axis_size=8, spec=SPEC, hide_ms=0.01)
    a, b = tune_bucket_bytes(**kw), tune_bucket_bytes(**kw)
    assert a == b and a in DEFAULT_CANDIDATES
    # nothing is hideable under 0.01 ms; the pick minimizes total
    # exposed wire across the ladder
    def exposed(c):
        B = -(-(256 << 20) // c)
        return B * (bucket_wire_ms(c, 8, SPEC) - 0.01 / B)
    assert all(exposed(a) <= exposed(c) + 1e-12
               for c in DEFAULT_CANDIDATES)


def test_tune_falls_back_loudly_on_unpriceable():
    for kw in (dict(program=None, grad_bytes=4 << 20, axis_size=4),
               dict(grad_bytes=0, axis_size=4),
               dict(grad_bytes=4 << 20, axis_size=4, hide_ms=0.0),
               dict(program=object(), grad_bytes=4 << 20, axis_size=4)):
        with pytest.warns(UserWarning, match="DEFAULT_BUCKET_BYTES"):
            assert tune_bucket_bytes(**kw) == DEFAULT_BUCKET_BYTES


def test_tune_prices_a_real_program():
    """The program path: a traced fwd+bwd prices to a positive hide
    window and resolves without the fallback warning."""
    w = jnp.ones((64, 64), jnp.float32)
    x = jnp.ones((8, 64), jnp.float32)

    def fwd_bwd(w, x):
        return jax.grad(lambda w: jnp.sum(jnp.tanh(x @ w) ** 2))(w)

    traced = jax.jit(fwd_bwd).trace(w, x)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        picked = tune_bucket_bytes(traced, grad_bytes=8 << 20,
                                   axis_size=4, spec=SPEC)
    assert picked in DEFAULT_CANDIDATES


# ---------------------------------------------------------------------------
# "auto" through the trainer
# ---------------------------------------------------------------------------

def test_trainer_resolves_auto_deterministically():
    from apex_tpu.observability.registry import get_registry
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    cfg = _cfg().fastpath()          # ddp_bucket_bytes == "auto"
    mesh = cfg.initialize_mesh(devices=jax.devices()[:4])
    try:
        tr1 = GPTHybridTrainer(cfg, mesh)
        tr2 = GPTHybridTrainer(cfg, mesh)
        assert isinstance(tr1.bucket_bytes, int)
        assert tr1.bucket_bytes == tr2.bucket_bytes
        # the resolved grid is stored back into the config — sidecars
        # and bucket_stamp both see the concrete int, never "auto"
        assert tr1.cfg.ddp_bucket_bytes == tr1.bucket_bytes
        assert tr1.opt.bucket_bytes == tr1.bucket_bytes
        g = get_registry().gauge("ddp/auto_bucket_bytes")
        assert g.is_set and g.value == float(tr1.bucket_bytes)
        # the ZeRO layout stamp a freshly-built state would carry is the
        # resolved grid (cheap check — no init compile; the stamp's
        # restore-boundary behavior is covered in test_dp_overlap)
        assert int(tr1.opt._stamp()) == tr1.bucket_bytes
    finally:
        parallel_state.destroy_model_parallel()


def test_trainer_rejects_bogus_bucket_spelling():
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    import dataclasses
    cfg = dataclasses.replace(_cfg(), ddp_bucket_bytes="4MiB")
    mesh = cfg.initialize_mesh(devices=jax.devices()[:4])
    try:
        with pytest.raises(ValueError, match='"auto"'):
            GPTHybridTrainer(cfg, mesh)
    finally:
        parallel_state.destroy_model_parallel()


def test_build_optimizer_refuses_unresolved_auto():
    import dataclasses
    cfg = dataclasses.replace(_cfg().fastpath())
    with pytest.raises(ValueError, match="resolved before"):
        cfg.build_optimizer()


# ---------------------------------------------------------------------------
# the compound structural assertion (satellite: PRs 2/4 asserted together)
# ---------------------------------------------------------------------------

def _compound_jaxpr_checks(tp, dp):
    from apex_tpu.optimizers._flatten import bucket_bounds
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    bb = 1024
    cfg = _cfg(tp=tp, pp=1, dp=dp).fastpath(bucket_bytes=bb)
    M, mb, seq = 2, 2, 8
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    targets = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    mesh = cfg.initialize_mesh(devices=jax.devices()[: tp * dp])
    try:
        tr = GPTHybridTrainer(cfg, mesh)
        # abstract state: the structural assertions only need avals, so
        # nothing in this test compiles or executes
        state = jax.eval_shape(tr.init_state, jax.random.PRNGKey(0))
        lay = tr.opt._layout
        assert lay is not None
        bounds = bucket_bounds(lay, bb)
        B = len(bounds)
        assert B > 1
        jaxpr = jax.make_jaxpr(tr.train_step)(*state, tokens, targets)

        def data_axis(eqn):
            return "data" in eqn_axes(eqn)

        # PR-4 contract, on the COMPOUND program: B data-axis
        # reduce-scatters, B per-bucket gathers (invariant all_gather or
        # the documented psum fallback), no full-tree psum
        n_rs = count_eqns(jaxpr, "reduce_scatter", where=data_axis)
        assert n_rs == B, (n_rs, B)
        n_ag = count_eqns(jaxpr, "all_gather", where=data_axis) \
            + count_eqns(jaxpr, "all_gather_invariant", where=data_axis)
        sizes = {n for _, n in bounds}
        n_fallback = count_eqns(
            jaxpr, "psum", where=lambda e: data_axis(e) and any(
                v.aval.ndim == 1 and v.aval.size in sizes
                for v in e.invars))
        assert n_ag == B or n_fallback >= B, (n_ag, n_fallback, B)
        assert count_eqns(
            jaxpr, "psum", where=lambda e: data_axis(e) and any(
                v.aval.ndim == 1 and v.aval.size == lay.padded
                for v in e.invars)) == 0
        # the backward-interleave contract: the padded flat vector never
        # materializes anywhere in the compound step
        flat_outs = flat_materializations(jaxpr.jaxpr, lay.padded)
        assert not flat_outs, flat_outs
        # PR-2 contract on the same program: zero fused
        # all_gather/reduce_scatter INSIDE the wired TP layers (their
        # named_scope regions) — at tp>1 with overlap on, the rings
        # replaced them; the data-axis ZeRO collectives above are
        # outside these scopes by construction
        wired = ("tp_column_linear", "tp_row_linear")
        fused_in_layers = [
            eqn.primitive.name for eqn in iter_eqns(jaxpr.jaxpr)
            if eqn.primitive.name in ("all_gather", "reduce_scatter")
            and any(w in str(eqn.source_info.name_stack) for w in wired)]
        assert not fused_in_layers, fused_in_layers
        if tp > 1 and cfg.model.tp_comm_overlap:
            # the rings are really there (tp-1 hops per ring, scanned)
            assert count_eqns(jaxpr, "ppermute") > 0
        return cfg
    finally:
        parallel_state.destroy_model_parallel()


def test_fastpath_compound_jaxpr_tp2():
    """The full compound assertion at tp=2 x dp=4: on VMA jax the preset
    carries SP+tp_comm_overlap and the TP-layer scopes must hold zero
    fused collectives next to the B-bucket ZeRO structure; on the
    pre-VMA 0.4.x line the preset degrades SP off (the trainer would
    refuse it) and the same DP/ZeRO/interleave assertions run on
    plain-TP — either way every per-feature assertion from PRs 2/4
    holds on ONE program. (The tp=1 shape of the same checks runs in
    the multichip dryrun gate's fastpath leg.)"""
    cfg = _compound_jaxpr_checks(tp=2, dp=4)
    assert cfg.model.tp_comm_overlap == HAS_VMA


# ---------------------------------------------------------------------------
# the bench leg
# ---------------------------------------------------------------------------

def test_bench_gpt_fast_smoke(monkeypatch):
    """bench_gpt_fast end to end on the 8-virtual-device mesh with
    shrunken shapes: both trainer legs compile and run, the emitted line
    carries the A/B ratio, the resolved auto bucket grid, and a config
    block of real field names."""
    import bench

    monkeypatch.setattr(bench, "_RESULTS", [])
    monkeypatch.setitem(
        bench.BENCH_TRAIN_CONFIGS, "gpt_base",
        {"model": {"name": "gpt", "vocab_size": 64, "hidden_size": 32,
                   "num_layers": 2, "num_attention_heads": 4,
                   "max_position_embeddings": 8},
         "optimizer": {"name": "adam", "lr": 1e-3},
         "opt_level": "O0"})
    bench.bench_gpt_fast(iters=2, warmup=1, mb=2, seq=8, max_devices=2)
    line = bench._RESULTS[-1]
    assert line["metric"] == "gpt_fast_tokens_per_sec"
    assert line["unit"] == "tokens/sec" and line["value"] > 0
    assert line["vs_baseline"] > 0 and line["base_tps"] > 0
    cfg = line["config"]
    assert cfg["model"]["remat_policy"] == "selective"
    assert cfg["optimizer"]["zero"] == 1
    assert isinstance(cfg["ddp_bucket_bytes"], int)  # "auto" resolved


# ---------------------------------------------------------------------------
# numerics: the compound configuration is a schedule, not a math change
# ---------------------------------------------------------------------------

def test_fastpath_parity_with_plain_trainer():
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    M, mb, seq, dp = 2, 2, 8, 2
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    targets = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))

    def run(cfg, steps=2):
        mesh = cfg.initialize_mesh(devices=jax.devices()[:dp])
        try:
            tr = GPTHybridTrainer(cfg, mesh)
            state = tr.init_state(jax.random.PRNGKey(0))
            step = jax.jit(tr.train_step)
            losses = []
            for _ in range(steps):
                loss, *state = step(*state, tokens, targets)
                losses.append(float(loss))
            return losses, state
        finally:
            parallel_state.destroy_model_parallel()

    l_ref, s_ref = run(_cfg(dp=dp))
    l_fast, s_fast = run(_cfg(dp=dp).fastpath(bucket_bytes=1024))
    np.testing.assert_allclose(l_fast, l_ref, rtol=1e-6, atol=1e-7)
    for pa, pb in zip(jax.tree_util.tree_leaves((s_ref[0], s_ref[1])),
                      jax.tree_util.tree_leaves((s_fast[0], s_fast[1]))):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=3e-6, atol=3e-6)
