"""Expert-parallel MoE tests (beyond-reference: SURVEY §2.3 lists EP as
roadmap; the reference has none)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.expert_parallel import ExpertParallelMLP

EP = 4


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:EP]), ("expert",))


def _dense_reference(layer, params, x_shards):
    """Per-shard top-1 routing applied densely (no capacity drops)."""
    Wg = np.asarray(params["router"]["weight"], np.float64)
    outs = []
    for xs in x_shards:
        xs64 = np.asarray(xs, np.float64)
        gates = jax.nn.softmax(jnp.asarray(xs64 @ Wg.T), axis=-1)
        gates = np.asarray(gates)
        expert = gates.argmax(-1)
        out = np.zeros_like(xs64)
        for i, e in enumerate(expert):
            wi = np.asarray(params["experts"]["wi"][e], np.float64)
            bi = np.asarray(params["experts"]["bi"][e], np.float64)
            wo = np.asarray(params["experts"]["wo"][e], np.float64)
            bo = np.asarray(params["experts"]["bo"][e], np.float64)
            h1 = np.asarray(jax.nn.gelu(jnp.asarray(xs64[i] @ wi.T + bi),
                                        approximate=True))
            out[i] = gates[i, e] * (h1 @ wo.T + bo)
        outs.append(out)
    return np.concatenate(outs)


def test_moe_matches_dense_reference(mesh):
    rng = np.random.RandomState(0)
    layer = ExpertParallelMLP(16, 32, num_experts=8, capacity_factor=8.0,
                              axis_name="expert")
    params = layer.init(jax.random.PRNGKey(0))
    x = jnp.asarray(rng.randn(EP * 12, 16), jnp.float32)

    def run(params, x):
        def inner(params, x):
            out, aux = layer(params, x)
            return out, jax.lax.pmean(aux, "expert")
        espec = {"router": {"weight": P()},
                 "experts": jax.tree_util.tree_map(lambda _: P("expert"),
                                                   params["experts"])}
        return shard_map(inner, mesh=mesh, in_specs=(espec, P("expert")),
                         out_specs=(P("expert"), P()))(params, x)

    out, aux = jax.jit(run)(params, x)
    ref = _dense_reference(layer, params,
                           np.split(np.asarray(x), EP))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)
    assert float(aux) >= 1.0 - 1e-5  # Switch aux lower bound at balance


def test_moe_capacity_drops_tokens(mesh):
    rng = np.random.RandomState(1)
    layer = ExpertParallelMLP(8, 16, num_experts=4, capacity_factor=0.25,
                              axis_name="expert")
    params = layer.init(jax.random.PRNGKey(1))
    x = jnp.asarray(rng.randn(EP * 16, 8), jnp.float32)

    def run(params, x):
        espec = {"router": {"weight": P()},
                 "experts": jax.tree_util.tree_map(lambda _: P("expert"),
                                                   params["experts"])}
        return shard_map(lambda p, x: layer(p, x)[0], mesh=mesh,
                         in_specs=(espec, P("expert")),
                         out_specs=P("expert"))(params, x)

    out = np.asarray(jax.jit(run)(params, x))
    zero_rows = np.all(out == 0.0, axis=-1).mean()
    assert zero_rows > 0.2  # capacity 1/token-per-expert drops plenty


def test_moe_grads_flow_to_router_and_experts(mesh):
    rng = np.random.RandomState(2)
    layer = ExpertParallelMLP(8, 16, num_experts=4, capacity_factor=4.0,
                              axis_name="expert")
    params = layer.init(jax.random.PRNGKey(2))
    x = jnp.asarray(rng.randn(EP * 8, 8), jnp.float32)

    def loss(params, x):
        espec = {"router": {"weight": P()},
                 "experts": jax.tree_util.tree_map(lambda _: P("expert"),
                                                   params["experts"])}

        def inner(params, x):
            out, aux = layer(params, x)
            return (jax.lax.psum(jnp.sum(out ** 2), "expert")
                    + 0.01 * jax.lax.pmean(aux, "expert"))
        return shard_map(inner, mesh=mesh, in_specs=(espec, P("expert")),
                         out_specs=P())(params, x)

    g = jax.jit(jax.grad(loss))(params, x)
    assert float(np.abs(np.asarray(g["router"]["weight"])).max()) > 0
    assert float(np.abs(np.asarray(g["experts"]["wi"])).max()) > 0
