"""MHA module parity vs torch.nn.MultiheadAttention
(``reference:apex/contrib/test/multihead_attn/test_*`` role: fast impl vs
the default framework impl)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.ops.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn

T, B, H, NH = 12, 3, 32, 4


def _torch_mha(embed_dim, heads):
    m = torch.nn.MultiheadAttention(embed_dim, heads, bias=False)
    m.eval()
    return m


def test_self_attn_matches_torch():
    attn = SelfMultiheadAttn(H, NH, bias=False)
    params = attn.init(jax.random.PRNGKey(0))
    tm = _torch_mha(H, NH)
    with torch.no_grad():
        tm.in_proj_weight.copy_(torch.tensor(
            np.asarray(params["qkv"]["weight"])))
        tm.out_proj.weight.copy_(torch.tensor(
            np.asarray(params["out"]["weight"])))

    x = np.random.RandomState(1).randn(T, B, H).astype(np.float32)
    out = attn(params, jnp.asarray(x))
    tout, _ = tm(torch.tensor(x), torch.tensor(x), torch.tensor(x))
    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=2e-4, atol=2e-5)


def test_self_attn_padding_and_causal_match_torch():
    attn = SelfMultiheadAttn(H, NH, bias=False)
    params = attn.init(jax.random.PRNGKey(2))
    tm = _torch_mha(H, NH)
    with torch.no_grad():
        tm.in_proj_weight.copy_(torch.tensor(
            np.asarray(params["qkv"]["weight"])))
        tm.out_proj.weight.copy_(torch.tensor(
            np.asarray(params["out"]["weight"])))

    rng = np.random.RandomState(3)
    x = rng.randn(T, B, H).astype(np.float32)
    pad = np.zeros((B, T), bool)
    pad[:, -3:] = True

    out = attn(params, jnp.asarray(x),
               key_padding_mask=jnp.asarray(pad))
    tout, _ = tm(torch.tensor(x), torch.tensor(x), torch.tensor(x),
                 key_padding_mask=torch.tensor(pad))
    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=2e-4, atol=2e-4)

    causal = torch.triu(torch.ones(T, T, dtype=torch.bool), diagonal=1)
    out_c = attn(params, jnp.asarray(x), attn_mask_causal=True)
    tout_c, _ = tm(torch.tensor(x), torch.tensor(x), torch.tensor(x),
                   attn_mask=causal)
    np.testing.assert_allclose(np.asarray(out_c), tout_c.detach().numpy(),
                               rtol=2e-4, atol=2e-5)


def test_self_attn_norm_add_and_grads():
    attn = SelfMultiheadAttn(H, NH, bias=True, include_norm_add=True)
    params = attn.init(jax.random.PRNGKey(4))
    x = jnp.asarray(np.random.RandomState(5).randn(T, B, H), jnp.float32)
    out = attn(params, x)
    assert out.shape == x.shape
    # norm-add is residual + attn(LN(x)): zeroing the out-proj leaves x
    zeroed = jax.tree_util.tree_map(jnp.zeros_like, params["out"])
    p2 = dict(params, out=zeroed)
    np.testing.assert_allclose(np.asarray(attn(p2, x)), np.asarray(x),
                               rtol=1e-6)
    g = jax.grad(lambda p: jnp.sum(attn(p, x) ** 2))(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_encdec_attn_matches_torch():
    attn = EncdecMultiheadAttn(H, NH, bias=False)
    params = attn.init(jax.random.PRNGKey(6))
    tm = _torch_mha(H, NH)
    with torch.no_grad():
        w = np.concatenate([np.asarray(params["q"]["weight"]),
                            np.asarray(params["kv"]["weight"])], axis=0)
        tm.in_proj_weight.copy_(torch.tensor(w))
        tm.out_proj.weight.copy_(torch.tensor(
            np.asarray(params["out"]["weight"])))

    rng = np.random.RandomState(7)
    q = rng.randn(T, B, H).astype(np.float32)
    mem = rng.randn(T + 4, B, H).astype(np.float32)
    out = attn(params, jnp.asarray(q), jnp.asarray(mem))
    tout, _ = tm(torch.tensor(q), torch.tensor(mem), torch.tensor(mem))
    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=2e-4, atol=2e-5)


def test_dropout_path_runs():
    attn = SelfMultiheadAttn(H, NH, dropout=0.3)
    params = attn.init(jax.random.PRNGKey(8))
    x = jnp.asarray(np.random.RandomState(9).randn(T, B, H), jnp.float32)
    out1 = attn(params, x, dropout_rng=jax.random.PRNGKey(1))
    out2 = attn(params, x, dropout_rng=jax.random.PRNGKey(2))
    assert not np.allclose(np.asarray(out1), np.asarray(out2))
    # eval (no rng) is deterministic
    np.testing.assert_allclose(np.asarray(attn(params, x)),
                               np.asarray(attn(params, x)))
