"""Smoke tests: the examples/ scripts (the reference's L5 layer) must run
end to end on the CPU mesh."""

import json
import sys

import numpy as np

import pytest

sys.path.insert(0, "examples")


def test_simple_distributed_runs():
    import simple_distributed
    loss = simple_distributed.main(steps=15)
    assert loss < 1.0


def test_imagenet_amp_runs_and_resumes(tmp_path):
    import imagenet_amp
    first = imagenet_amp.main(["--steps", "2", "--per-device-batch", "1",
                               "--img", "32", "--opt-level", "O2",
                               "--ckpt-dir", str(tmp_path)])
    assert np.isfinite(first)
    # resume picks up at step 2
    loss = imagenet_amp.main(["--steps", "1", "--per-device-batch", "1",
                              "--img", "32", "--opt-level", "O2",
                              "--ckpt-dir", str(tmp_path)])
    assert np.isfinite(loss)


def test_gpt_pretrain_runs_and_serves_metrics_port():
    """The pretrain example, also exercising --metrics-port (one run,
    not two — tier-1 budget): the single-process face of the fleet
    endpoint. /metrics serves the LOCAL registry in Prometheus text
    exposition over a real HTTP round-trip while the server is live
    (port 0 = ephemeral), carrying the train-side step counter."""
    import urllib.request

    import gpt_pretrain

    from apex_tpu.observability import get_registry

    get_registry().counter("train/steps").reset()
    seen = {}

    def fetch(base_url):
        with urllib.request.urlopen(base_url + "/metrics",
                                    timeout=10) as r:
            seen["status"] = r.status
            seen["text"] = r.read().decode()

    loss = gpt_pretrain.main(["--tp", "2", "--pp", "2", "--steps", "2",
                              "--metrics-port", "0"], on_metrics=fetch)
    assert loss > 0
    assert seen["status"] == 200
    text = seen["text"]
    assert "train_steps 2" in text
    # parses as Prometheus text exposition: every sample line is
    # "name value" with a float-spellable value
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        assert name, line
        float(value)


def test_gpt_pretrain_zero_runs():
    """--zero swaps in the ZeRO sharded optimizer (DistributedFusedAdam)
    inside the same hybrid trainer — here with --bucket-bytes, so the
    example drives the per-bucket reduce_scatter/all_gather overlap path;
    the loss trajectory must stay finite and positive."""
    import gpt_pretrain
    loss = gpt_pretrain.main(["--tp", "2", "--pp", "2", "--steps", "2",
                              "--zero", "--bucket-bytes", "4096"])
    assert loss > 0


def test_gpt_pretrain_elastic_checkpoint_and_resume(tmp_path):
    """--checkpoint-dir routes the example through the elastic runtime:
    the first invocation checkpoints as it trains, the second resumes
    from the latest COMMITTED step and runs only the remaining steps."""
    import gpt_pretrain

    from apex_tpu.checkpoint import all_steps, latest_step

    args = ["--tp", "2", "--pp", "2", "--checkpoint-dir", str(tmp_path),
            "--save-interval", "1", "--keep-last", "2"]
    loss = gpt_pretrain.main(args + ["--steps", "2"])
    assert np.isfinite(loss)
    assert latest_step(str(tmp_path)) == 2
    assert len(all_steps(str(tmp_path))) <= 2  # keep_last GC bound
    loss2 = gpt_pretrain.main(args + ["--steps", "3"])
    assert np.isfinite(loss2)
    assert latest_step(str(tmp_path)) == 3


def test_gpt_serve_runs(tmp_path):
    """The serving demo: every request completes through the continuous
    batcher, the serve/* surface is populated, and the
    percentile/goodput summary (the bench_gpt_decode vocabulary) plus
    the per-slot Chrome request trace come out (docs/SERVING.md)."""
    import gpt_serve
    trace_path = tmp_path / "req_trace.json"
    payload = gpt_serve.main(["--requests", "4", "--max-new-tokens", "4",
                              "--trace-out", str(trace_path)])
    results = payload["completions"]
    assert sorted(results) == list(range(4))
    for i, c in sorted(results.items()):
        assert len(c.tokens) == 1 + (4 * (i + 1)) // 2
        assert c.finish_reason == "length"
        # completions carry the measured request latencies
        assert c.queue_wait_ms >= 0.0
        assert c.ttft_ms >= c.queue_wait_ms
        assert c.e2e_ms >= c.ttft_ms and c.tpot_ms > 0.0
    m = payload["metrics"]
    assert m["serve/admitted"] == 4.0 and m["serve/retired"] == 4.0
    assert m["serve/generated_tokens"] == sum(
        1 + (4 * (i + 1)) // 2 for i in range(4))
    assert m["serve/tokens_per_sec"] > 0.0
    # the latency/SLO summary: p50 <= p95 <= p99, all measured
    lat = payload["latency"]
    for short in ("ttft", "tpot", "queue_wait", "e2e"):
        p50, p95, p99 = (lat[f"{short}_p{q}_ms"] for q in (50, 95, 99))
        assert 0.0 <= p50 <= p95 <= p99, short
    assert lat["ttft_p50_ms"] > 0.0
    assert 0.0 <= payload["goodput"] <= 1.0
    assert payload["slo"] and "ttft_ms p95" in payload["slo"][0]
    # the Chrome request trace is strict JSON with per-slot lanes
    doc = json.loads(trace_path.read_text())
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert lanes == {"queue", "slot 0", "slot 1"}
    # resilience counts ride the payload: nothing rejected or expired
    # in an unconstrained run
    assert payload["rejected"] == 0 and payload["expired"] == 0


def test_gpt_serve_resilience_flags():
    """--max-queue bounds admission with typed rejections and
    --deadline-ms expires overdue requests — the counts the demo prints
    (docs/SERVING.md "Resilience")."""
    import gpt_serve
    # every request is submitted before the loop starts, so a 6-request
    # run against --max-queue 2 deterministically rejects 4
    payload = gpt_serve.main(["--requests", "6", "--max-new-tokens", "2",
                              "--max-queue", "2"])
    assert payload["rejected"] == 4 and payload["expired"] == 0
    assert [r.reason for r in payload["rejections"]] == ["queue_full"] * 4
    assert len(payload["completions"]) == 2  # the two that fit served
    # a microscopic default deadline expires everything in the queue
    payload = gpt_serve.main(["--requests", "3", "--max-new-tokens", "2",
                              "--deadline-ms", "0.001"])
    assert payload["expired"] == 3 and payload["rejected"] == 0
    assert all(c.finish_reason == "expired"
               for c in payload["completions"].values())


def test_gpt_serve_speculative_flag():
    """--speculate-k serves the same request mix through the verify
    program and prints the acceptance rate plus the TPOT delta against
    a same-session non-speculative baseline (docs/SERVING.md
    "Speculative decoding"). Greedy requests must complete with their
    exact lengths — speculation changes the stepping, never the
    stream."""
    import gpt_serve
    payload = gpt_serve.main(["--requests", "4", "--max-new-tokens", "6",
                              "--speculate-k", "3"])
    results = payload["completions"]
    assert sorted(results) == list(range(4))
    for i, c in sorted(results.items()):
        assert len(c.tokens) == 1 + (6 * (i + 1)) // 2
        assert c.finish_reason == "length"
    spec = payload["spec"]
    assert spec["k"] == 3
    assert 0.0 <= spec["accept_rate"] <= 1.0
    assert spec["drafted"] > 0 and spec["spec_steps"] > 0
    assert spec["accepted"] == round(spec["accept_rate"]
                                     * spec["drafted"])
    # the A/B carries both TPOT medians and their delta
    assert spec["tpot_p50_ms"] > 0.0 and spec["baseline_tpot_p50_ms"] > 0.0
    assert spec["tpot_delta_ms"] == round(
        spec["baseline_tpot_p50_ms"] - spec["tpot_p50_ms"], 2)
    # without the flag the payload says so explicitly
    assert gpt_serve.main(["--requests", "2",
                           "--max-new-tokens", "2"])["spec"] is None


def test_dcgan_amp_runs():
    import dcgan_amp
    errD, errG = dcgan_amp.main(["--steps", "3", "--batch", "8"])
    assert np.isfinite(errD) and np.isfinite(errG)


def test_long_context_example_runs():
    import long_context
    val = long_context.main(["--seq-per-device", "64"])
    assert np.isfinite(val)


def test_telemetry_example_runs(tmp_path):
    """The observability worked example: 3 steps must stream the full
    documented metric surface and a loadable Chrome trace."""
    import telemetry
    payload = telemetry.main(["--steps", "3", "--out-dir", str(tmp_path)])
    for key in ("loss", "amp/loss_scale", "ddp/allreduce_bytes",
                "optim/grad_norm", "pipeline/bubble_fraction"):
        assert key in payload
    assert (tmp_path / "telemetry.jsonl").exists()
    assert (tmp_path / "host_trace.json").exists()
    # the health-watchdog demo ran: the injected inf produced an
    # attributed crash dump
    dumps = list(tmp_path.glob("health_dump_step*.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["attribution"] == {"grads": "['bad']"}
    assert doc["metrics"]["health/grads/nonfinite_count"] == 2.0
