"""Direct coverage for tensor_parallel/mappings.py forward/transpose pairs.

The four Megatron mapping pairs (copy/reduce/scatter/gather) were only
exercised indirectly through the GPT model; these tests pin each forward
collective and its AD transpose on a 2-device tensor mesh, plus the
divisibility guards (a floor-divide used to silently drop elements).
Models ``reference:tests/L0/run_transformer/test_mapping.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.context_parallel import (
    gather_from_sequence_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region)
from apex_tpu.utils.compat import shard_map


@pytest.fixture
def mesh_tp2():
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    yield mesh
    parallel_state.destroy_model_parallel()


def _smap(mesh, fn, in_specs, out_specs):
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))


# ---------------------------------------------------------------------------
# copy: identity forward / allreduce backward
# ---------------------------------------------------------------------------

def test_copy_forward_identity_backward_psum(mesh_tp2):
    x = jnp.asarray(np.random.RandomState(0).randn(3, 4), jnp.float32)

    fwd = _smap(mesh_tp2,
                lambda x: jax.lax.pmean(
                    tp.copy_to_tensor_model_parallel_region(x), "tensor"),
                (P(),), P())
    np.testing.assert_array_equal(np.asarray(fwd(x)), np.asarray(x))

    # each rank consumes the copy independently; the transpose allreduces,
    # so d(sum over ranks of sum(x*r_weight)) = tp * x-grad-per-rank
    def loss(x):
        def inner(x):
            y = tp.copy_to_tensor_model_parallel_region(x)
            return jax.lax.psum(jnp.sum(y ** 2), "tensor") / 2.0
        return shard_map(inner, mesh=mesh_tp2, in_specs=(P(),),
                         out_specs=P())(x)

    g = jax.jit(jax.grad(loss))(x)
    np.testing.assert_allclose(np.asarray(g), 2.0 * np.asarray(x),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# reduce: allreduce forward / identity backward
# ---------------------------------------------------------------------------

def test_reduce_forward_sum_backward_identity(mesh_tp2):
    x = jnp.asarray(np.random.RandomState(1).randn(4, 6), jnp.float32)

    # x sharded over the last dim: each rank holds a distinct half; the
    # reduce sums rank-local squares into a replicated total
    def fwd(x):
        def inner(x):
            return tp.reduce_from_tensor_model_parallel_region(
                jnp.sum(x ** 2))
        return shard_map(inner, mesh=mesh_tp2, in_specs=(P(None, "tensor"),),
                         out_specs=P())(x)

    total = jax.jit(fwd)(x)
    np.testing.assert_allclose(float(total), float(jnp.sum(x ** 2)),
                               rtol=1e-6)
    # transpose of psum = identity-as-varying: plain d/dx of the total
    g = jax.jit(jax.grad(lambda x: fwd(x)))(x)
    np.testing.assert_allclose(np.asarray(g), 2.0 * np.asarray(x),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# scatter/gather: round trips both ways + transposes
# ---------------------------------------------------------------------------

def test_scatter_gather_roundtrip(mesh_tp2):
    x = jnp.asarray(np.random.RandomState(2).randn(4, 8), jnp.float32)

    def roundtrip(x):
        def inner(x):
            s = tp.scatter_to_tensor_model_parallel_region(x)
            g = tp.gather_from_tensor_model_parallel_region(s)
            return jax.lax.pmean(g, "tensor")
        return shard_map(inner, mesh=mesh_tp2, in_specs=(P(),),
                         out_specs=P())(x)

    np.testing.assert_allclose(np.asarray(jax.jit(roundtrip)(x)),
                               np.asarray(x), rtol=1e-6)

    # gather-then-scatter on sharded input is also identity (rank keeps
    # its own slice of the gathered value)
    def gs(x):
        def inner(x):
            g = tp.gather_from_tensor_model_parallel_region(x)
            return tp.scatter_to_tensor_model_parallel_region(g)
        return shard_map(inner, mesh=mesh_tp2,
                         in_specs=(P(None, "tensor"),),
                         out_specs=P(None, "tensor"))(x)

    np.testing.assert_allclose(np.asarray(jax.jit(gs)(x)), np.asarray(x),
                               rtol=1e-6)

    # scatter transpose: every element of x is consumed by exactly one
    # rank, so d(sum over ranks of sum(shard^2)) = 2x everywhere
    def loss(x):
        def inner(x):
            s = tp.scatter_to_tensor_model_parallel_region(x)
            return jax.lax.psum(jnp.sum(s ** 2), "tensor")
        return shard_map(inner, mesh=mesh_tp2, in_specs=(P(),),
                         out_specs=P())(x)

    g = jax.jit(jax.grad(loss))(x)
    np.testing.assert_allclose(np.asarray(g), 2.0 * np.asarray(x),
                               rtol=1e-6)

    # gather transpose: the gathered value feeds a replicated-weighted sum
    # on every rank; the reduce-scatter transpose hands each shard the sum
    # of its cotangents over ranks (= tp * its slice weight here)
    def loss_g(x):
        def inner(x):
            g = tp.gather_from_tensor_model_parallel_region(x)
            return jax.lax.psum(jnp.sum(g ** 2), "tensor") / 2.0
        return shard_map(inner, mesh=mesh_tp2,
                         in_specs=(P(None, "tensor"),),
                         out_specs=P())(x)

    g2 = jax.jit(jax.grad(loss_g))(x)
    np.testing.assert_allclose(np.asarray(g2), 2.0 * np.asarray(x),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# sequence-parallel mappings (context_parallel.py)
# ---------------------------------------------------------------------------

def test_sp_scatter_gather_roundtrip_and_reduce_scatter(mesh_tp2):
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 4), jnp.float32)

    def roundtrip(x):
        def inner(x):
            s = scatter_to_sequence_parallel_region(x, "tensor", seq_axis=1)
            return gather_from_sequence_parallel_region(
                s, "tensor", seq_axis=1, invariant=True)
        return shard_map(inner, mesh=mesh_tp2, in_specs=(P(),),
                         out_specs=P())(x)

    np.testing.assert_allclose(np.asarray(jax.jit(roundtrip)(x)),
                               np.asarray(x), rtol=1e-6)

    # psum_scatter: each rank contributes the full sequence; shard r of the
    # output is the rank-sum of shard r of the contributions
    def rs(x):
        def inner(x):
            from apex_tpu.utils.vma import cast_to_vma
            contrib = cast_to_vma(x, frozenset({"tensor"}))
            return reduce_scatter_to_sequence_parallel_region(
                contrib, "tensor", seq_axis=1)
        return shard_map(inner, mesh=mesh_tp2, in_specs=(P(),),
                         out_specs=P(None, "tensor", None))(x)

    out = jax.jit(rs)(x)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.asarray(x),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# divisibility guards (the silent-truncation fix)
# ---------------------------------------------------------------------------

def test_scatter_rejects_indivisible_last_dim(mesh_tp2):
    x = jnp.ones((4, 7))  # 7 % 2 != 0: used to silently drop an element

    def run(x):
        return shard_map(
            lambda x: tp.scatter_to_tensor_model_parallel_region(x),
            mesh=mesh_tp2, in_specs=(P(),),
            out_specs=P(None, "tensor"))(x)

    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(run)(x)


def test_sp_scatter_rejects_indivisible_seq(mesh_tp2):
    x = jnp.ones((2, 7, 4))

    def run(x):
        return shard_map(
            lambda x: scatter_to_sequence_parallel_region(
                x, "tensor", seq_axis=1),
            mesh=mesh_tp2, in_specs=(P(),),
            out_specs=P(None, "tensor", None))(x)

    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(run)(x)
