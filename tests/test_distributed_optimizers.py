"""ZeRO sharded-optimizer tests (``reference:apex/contrib/test/optimizers/
test_dist_adam.py`` role): numeric parity with the dense optimizer + DDP,
and the 1/dp state-memory property that is ZeRO's point.

Runs on the 8-virtual-CPU-device mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.amp.scaler import all_finite
from apex_tpu.optimizers import (
    DistributedFusedAdam, DistributedFusedLAMB, FusedAdam, FusedLAMB,
    ZeroAdamState, ZeroLambState)

DP = 4


def _state_spec(opt):
    cls = ZeroAdamState if isinstance(opt, DistributedFusedAdam) \
        else ZeroLambState
    return cls(step=P(), master=P("data"), exp_avg=P("data"),
               exp_avg_sq=P("data"))


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:DP]), ("data",))


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(16, 33), jnp.float32),  # odd size: padding
        "b": jnp.asarray(rng.randn(33), jnp.float32),
        "emb": jnp.asarray(rng.randn(7, 16), jnp.float32),
    }


def _per_rank_grads(params, seed=1):
    """One distinct grad pytree per DP rank, stacked on axis 0."""
    rng = np.random.RandomState(seed)
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(DP, *np.shape(p)), jnp.float32), params)


def _run_zero(mesh, opt, params, grads_stacked, n_steps, grads_finite=None):
    """Jitted shard_map step loop: grads sharded over data (one replica's
    grads per device), params replicated in/out."""

    def stepper(params, grads_stacked):
        def inner(params, grads_stacked):
            state = opt.init(params)
            for i in range(n_steps):
                g = jax.tree_util.tree_map(lambda s: s[0], grads_stacked)
                params, state = opt.step(g, state, params,
                                         grads_finite=grads_finite)
            return params, state
        gspec = jax.tree_util.tree_map(lambda _: P("data"), grads_stacked)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(), gspec),
                         out_specs=(P(), _state_spec(opt)))(
                             params, grads_stacked)

    return jax.jit(stepper)(params, grads_stacked)


def _run_dense(opt, params, grads_stacked, n_steps):
    """Dense reference: DDP grad averaging is a plain mean over ranks."""
    state = opt.init(params)
    for _ in range(n_steps):
        g = jax.tree_util.tree_map(lambda s: jnp.mean(s, 0), grads_stacked)
        params, state = opt.step(g, state, params)
    return params, state


def test_zero_adam_matches_dense_ddp(mesh):
    params = _params()
    grads = _per_rank_grads(params)
    kw = dict(lr=1e-2, betas=(0.9, 0.95), eps=1e-8, weight_decay=0.01)
    zp, zstate = _run_zero(mesh, DistributedFusedAdam(**kw), params, grads, 3)
    dp_, _ = _run_dense(FusedAdam(**kw), params, grads, 3)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]), np.asarray(dp_[k]),
                                   rtol=2e-6, atol=2e-6)


def test_zero_adam_l2_mode(mesh):
    params = _params(2)
    grads = _per_rank_grads(params, 3)
    kw = dict(lr=1e-2, adam_w_mode=False, weight_decay=0.1)
    zp, _ = _run_zero(mesh, DistributedFusedAdam(**kw), params, grads, 2)
    dp_, _ = _run_dense(FusedAdam(**kw), params, grads, 2)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]), np.asarray(dp_[k]),
                                   rtol=2e-6, atol=2e-6)


def test_zero_lamb_matches_dense_ddp(mesh):
    params = _params(4)
    grads = _per_rank_grads(params, 5)
    kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    zp, _ = _run_zero(mesh, DistributedFusedLAMB(**kw), params, grads, 3)
    dp_, _ = _run_dense(FusedLAMB(**kw), params, grads, 3)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]), np.asarray(dp_[k]),
                                   rtol=2e-5, atol=2e-5)


def test_zero_state_is_sharded(mesh):
    """Per-device optimizer state is 1/dp of the dense state — the ZeRO
    memory win (reference distributed_fused_adam.py:202-207)."""
    params = _params()
    grads = _per_rank_grads(params)
    total = sum(int(np.prod(np.shape(p))) for p in
                jax.tree_util.tree_leaves(params))
    padded = ((total + DP - 1) // DP) * DP

    _, zstate = _run_zero(mesh, DistributedFusedAdam(lr=1e-3), params,
                          grads, 1)
    # out_specs P("data") stacks per-rank shards: global (dp*shard,), and
    # each device's addressable shard is padded/dp
    for leaf in (zstate.master, zstate.exp_avg, zstate.exp_avg_sq):
        assert leaf.shape == (padded,)
        assert leaf.addressable_shards[0].data.shape == (padded // DP,)


def test_zero_overflow_skip(mesh):
    params = _params()
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full((DP, *np.shape(p)), jnp.inf, jnp.float32), params)
    finite = all_finite(grads)
    zp, zstate = _run_zero(mesh, DistributedFusedAdam(lr=1e-2), params,
                           grads, 1, grads_finite=finite)
    for k in params:
        np.testing.assert_array_equal(np.asarray(zp[k]), np.asarray(params[k]))
    assert int(zstate.step) == 0  # step count did not advance


def test_zero_bf16_params_fp32_master(mesh):
    """bf16 params train through an fp32 master shard: the update applied at
    fp32 precision survives the roundtrip (amp O2 semantics)."""
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), _params(6))
    grads = jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32), _per_rank_grads(params, 7))
    zp, zstate = _run_zero(mesh, DistributedFusedAdam(lr=1e-3), params,
                           grads, 2)
    for k in params:
        assert zp[k].dtype == jnp.bfloat16
    # master is fp32 and differs from the bf16 roundtrip by < 1 bf16 ulp
    assert zstate.master.dtype == jnp.float32


def test_zero_step_compiles_to_three_collectives(mesh):
    """The module docstring's performance story: the whole ZeRO step is
    psum_scatter(grads) + [LAMB-only psums] + one all-gather of updated
    params — no hidden extra all-reduces. Counted in the compiled HLO
    (overlap itself is XLA's latency-hiding scheduler; the countable
    invariant is that there is nothing else to overlap-hide)."""
    try:
        from jax._src.lax.parallel import all_gather_invariant  # noqa: F401
    except ImportError:
        pytest.skip("this jax lacks all_gather_invariant; the param "
                    "gather lowers via the documented psum fallback, so "
                    "the 3-collective pattern doesn't apply")
    opt = DistributedFusedAdam(lr=1e-2)
    params = _params()
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    def step(params, grads):
        def inner(params, grads):
            state = opt.init(params)
            return opt.step(grads, state, params)[0]
        gspec = jax.tree_util.tree_map(lambda _: P(), grads)
        return shard_map(inner, mesh=mesh, in_specs=(P(), gspec),
                         out_specs=P())(params, grads)

    txt = jax.jit(step).lower(params, grads).compile().as_text()
    n_rs = txt.count("reduce-scatter(")
    n_ag = txt.count("all-gather(") + txt.count("all-gather-start(")
    n_ar = txt.count("all-reduce(") + txt.count("all-reduce-start(")
    assert n_rs == 1, txt.count("reduce-scatter")
    assert n_ag == 1
    assert n_ar == 0
