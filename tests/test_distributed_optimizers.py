"""ZeRO sharded-optimizer tests (``reference:apex/contrib/test/optimizers/
test_dist_adam.py`` role): numeric parity with the dense optimizer + DDP,
and the 1/dp state-memory property that is ZeRO's point.

Runs on the 8-virtual-CPU-device mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.amp.scaler import all_finite
from apex_tpu.optimizers import (
    DistributedFusedAdam, DistributedFusedLAMB, FusedAdam, FusedLAMB,
    ZeroAdamState, ZeroLambState)

DP = 4


def _state_spec(opt):
    cls = ZeroAdamState if isinstance(opt, DistributedFusedAdam) \
        else ZeroLambState
    return cls(step=P(), master=P("data"), exp_avg=P("data"),
               exp_avg_sq=P("data"), bucket_stamp=P())


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:DP]), ("data",))


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(16, 33), jnp.float32),  # odd size: padding
        "b": jnp.asarray(rng.randn(33), jnp.float32),
        "emb": jnp.asarray(rng.randn(7, 16), jnp.float32),
    }


def _per_rank_grads(params, seed=1):
    """One distinct grad pytree per DP rank, stacked on axis 0."""
    rng = np.random.RandomState(seed)
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(DP, *np.shape(p)), jnp.float32), params)


def _run_zero(mesh, opt, params, grads_stacked, n_steps, grads_finite=None):
    """Jitted shard_map step loop: grads sharded over data (one replica's
    grads per device), params replicated in/out."""

    def stepper(params, grads_stacked):
        def inner(params, grads_stacked):
            state = opt.init(params)
            for i in range(n_steps):
                g = jax.tree_util.tree_map(lambda s: s[0], grads_stacked)
                params, state = opt.step(g, state, params,
                                         grads_finite=grads_finite)
            return params, state
        gspec = jax.tree_util.tree_map(lambda _: P("data"), grads_stacked)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(), gspec),
                         out_specs=(P(), _state_spec(opt)))(
                             params, grads_stacked)

    return jax.jit(stepper)(params, grads_stacked)


def _run_dense(opt, params, grads_stacked, n_steps):
    """Dense reference: DDP grad averaging is a plain mean over ranks."""
    state = opt.init(params)
    for _ in range(n_steps):
        g = jax.tree_util.tree_map(lambda s: jnp.mean(s, 0), grads_stacked)
        params, state = opt.step(g, state, params)
    return params, state


def test_zero_adam_matches_dense_ddp(mesh):
    params = _params()
    grads = _per_rank_grads(params)
    kw = dict(lr=1e-2, betas=(0.9, 0.95), eps=1e-8, weight_decay=0.01)
    zp, zstate = _run_zero(mesh, DistributedFusedAdam(**kw), params, grads, 3)
    dp_, _ = _run_dense(FusedAdam(**kw), params, grads, 3)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]), np.asarray(dp_[k]),
                                   rtol=2e-6, atol=2e-6)


def test_zero_adam_l2_mode(mesh):
    params = _params(2)
    grads = _per_rank_grads(params, 3)
    kw = dict(lr=1e-2, adam_w_mode=False, weight_decay=0.1)
    zp, _ = _run_zero(mesh, DistributedFusedAdam(**kw), params, grads, 2)
    dp_, _ = _run_dense(FusedAdam(**kw), params, grads, 2)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]), np.asarray(dp_[k]),
                                   rtol=2e-6, atol=2e-6)


def test_zero_lamb_matches_dense_ddp(mesh):
    params = _params(4)
    grads = _per_rank_grads(params, 5)
    kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    zp, _ = _run_zero(mesh, DistributedFusedLAMB(**kw), params, grads, 3)
    dp_, _ = _run_dense(FusedLAMB(**kw), params, grads, 3)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]), np.asarray(dp_[k]),
                                   rtol=2e-5, atol=2e-5)


def test_zero_state_is_sharded(mesh):
    """Per-device optimizer state is 1/dp of the dense state — the ZeRO
    memory win (reference distributed_fused_adam.py:202-207)."""
    params = _params()
    grads = _per_rank_grads(params)
    total = sum(int(np.prod(np.shape(p))) for p in
                jax.tree_util.tree_leaves(params))
    padded = ((total + DP - 1) // DP) * DP

    _, zstate = _run_zero(mesh, DistributedFusedAdam(lr=1e-3), params,
                          grads, 1)
    # out_specs P("data") stacks per-rank shards: global (dp*shard,), and
    # each device's addressable shard is padded/dp
    for leaf in (zstate.master, zstate.exp_avg, zstate.exp_avg_sq):
        assert leaf.shape == (padded,)
        assert leaf.addressable_shards[0].data.shape == (padded // DP,)


def test_zero_overflow_skip(mesh):
    params = _params()
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full((DP, *np.shape(p)), jnp.inf, jnp.float32), params)
    finite = all_finite(grads)
    zp, zstate = _run_zero(mesh, DistributedFusedAdam(lr=1e-2), params,
                           grads, 1, grads_finite=finite)
    for k in params:
        np.testing.assert_array_equal(np.asarray(zp[k]), np.asarray(params[k]))
    assert int(zstate.step) == 0  # step count did not advance


def test_zero_bf16_params_fp32_master(mesh):
    """bf16 params train through an fp32 master shard: the update applied at
    fp32 precision survives the roundtrip (amp O2 semantics)."""
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), _params(6))
    grads = jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32), _per_rank_grads(params, 7))
    zp, zstate = _run_zero(mesh, DistributedFusedAdam(lr=1e-3), params,
                           grads, 2)
    for k in params:
        assert zp[k].dtype == jnp.bfloat16
    # master is fp32 and differs from the bf16 roundtrip by < 1 bf16 ulp
    assert zstate.master.dtype == jnp.float32


def test_zero_bucketed_matches_dense_ddp(mesh):
    """Per-bucket reduce-scatter/all-gather (bucket_bytes) keeps exact
    parity with the dense optimizer + DDP mean: the bucket grid only
    re-partitions the flat vector, every element sees the same fp32
    arithmetic (the reduction order inside each collective is the
    backend's, same as unbucketed)."""
    params = _params(8)
    grads = _per_rank_grads(params, 9)
    kw = dict(lr=1e-2, betas=(0.9, 0.95), eps=1e-8, weight_decay=0.01)
    dp_, _ = _run_dense(FusedAdam(**kw), params, grads, 3)
    for bb in (256, 4096):
        zp, _ = _run_zero(mesh, DistributedFusedAdam(**kw, bucket_bytes=bb),
                          params, grads, 3)
        for k in params:
            np.testing.assert_allclose(np.asarray(zp[k]), np.asarray(dp_[k]),
                                       rtol=2e-6, atol=2e-6)
    # LAMB: bucketed scatter/gather around the whole-shard trust-ratio math
    kwl = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    dl, _ = _run_dense(FusedLAMB(**kwl), params, grads, 2)
    zl, _ = _run_zero(mesh, DistributedFusedLAMB(**kwl, bucket_bytes=256),
                      params, grads, 2)
    for k in params:
        np.testing.assert_allclose(np.asarray(zl[k]), np.asarray(dl[k]),
                                   rtol=2e-5, atol=2e-5)


def test_zero_bucketed_state_is_sharded(mesh):
    """Bucketing re-orders the master shard (bucket-major) but never its
    size: per-device state stays padded/dp — the ZeRO memory win is
    bucket-size-independent."""
    from apex_tpu.optimizers._flatten import bucket_bounds, build_layout

    params = _params()
    grads = _per_rank_grads(params)
    total = sum(int(np.prod(np.shape(p))) for p in
                jax.tree_util.tree_leaves(params))
    padded = ((total + DP - 1) // DP) * DP
    opt = DistributedFusedAdam(lr=1e-3, bucket_bytes=256)
    _, zstate = _run_zero(mesh, opt, params, grads, 1)
    assert len(bucket_bounds(build_layout(params, chunks=DP), 256)) > 1
    for leaf in (zstate.master, zstate.exp_avg, zstate.exp_avg_sq):
        assert leaf.shape == (padded,)
        assert leaf.addressable_shards[0].data.shape == (padded // DP,)


def test_zero_bucketed_jaxpr_per_bucket_collectives(mesh):
    """B buckets -> exactly B data-axis reduce-scatters and B gathers in
    the step jaxpr (counted structurally; the gather is B invariant
    all-gathers where this jax has them, else B bucket-sized psums via the
    documented fallback)."""
    from _jaxpr_utils import count_eqns, eqn_axes
    from apex_tpu.optimizers._flatten import bucket_bounds, build_layout

    bb = 256
    opt = DistributedFusedAdam(lr=1e-2, bucket_bytes=bb)
    params = _params()
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    lay = build_layout(params, chunks=DP)
    bounds = bucket_bounds(lay, bb)
    B = len(bounds)
    assert B > 1

    def step(params, grads):
        def inner(params, grads):
            state = opt.init(params)
            return opt.step(grads, state, params)[0]
        gspec = jax.tree_util.tree_map(lambda _: P(), grads)
        return shard_map(inner, mesh=mesh, in_specs=(P(), gspec),
                         out_specs=P())(params, grads)

    jaxpr = jax.make_jaxpr(step)(params, grads)

    def on_data(eqn):
        return "data" in eqn_axes(eqn)

    assert count_eqns(jaxpr, "reduce_scatter", where=on_data) == B
    sizes = {n for _, n in bounds}
    n_ag = (count_eqns(jaxpr, "all_gather", where=on_data)
            + count_eqns(jaxpr, "all_gather_invariant", where=on_data))

    def psums(where):
        # 0.4.x check_rep shard_map rewrites psum to its psum2 variant
        return (count_eqns(jaxpr, "psum", where=where)
                + count_eqns(jaxpr, "psum2", where=where))

    n_fallback = psums(lambda e: on_data(e) and any(
        v.aval.ndim == 1 and v.aval.size in sizes for v in e.invars))
    assert n_ag == B or n_fallback >= B, (n_ag, n_fallback, B)
    # and never a monolithic reduction of the whole padded flat vector
    full = lambda e: on_data(e) and any(
        v.aval.ndim == 1 and v.aval.size == lay.padded for v in e.invars)
    assert psums(full) == 0
    assert count_eqns(jaxpr, "reduce_scatter", where=full) == (
        0 if B > 1 else 1)


def test_zero_bucket_grid_is_value_transparent(mesh):
    """bucket_bytes is a layout-internal property (it re-orders the master
    shard bucket-major but changes no values): bucketed and unbucketed
    optimizers produce the same parameter updates. The grid must be
    identical across init and step — guaranteed by construction, since the
    same opt object carries it (docstring contract)."""
    params = _params()
    grads = _per_rank_grads(params)
    kw = dict(lr=1e-2)
    zp_a, _ = _run_zero(mesh, DistributedFusedAdam(**kw, bucket_bytes=256),
                        params, grads, 1)
    zp_b, _ = _run_zero(mesh, DistributedFusedAdam(**kw), params, grads, 1)
    # different grids, same update values — the grid is layout-internal
    for k in params:
        np.testing.assert_allclose(np.asarray(zp_a[k]), np.asarray(zp_b[k]),
                                   rtol=2e-6, atol=2e-6)


def test_zero_bucket_grid_mismatch_is_loud(mesh):
    """A state built under one bucket grid must not be stepped under
    another — the shard order is bucket-major, so the mismatch would
    silently permute master params. check_state (and the eager _step)
    raises instead; the stamp round-trips through a save/restore since it
    is an ordinary state leaf."""
    params = _params()
    grads = _per_rank_grads(params)
    _, state = _run_zero(mesh, DistributedFusedAdam(lr=1e-2), params,
                         grads, 1)
    assert int(state.bucket_stamp) == 0  # monolithic stamp
    mismatched = DistributedFusedAdam(lr=1e-2, bucket_bytes=256)
    with pytest.raises(ValueError, match="bucket-major|bucket_bytes"):
        mismatched.check_state(state)
    # matching config passes
    DistributedFusedAdam(lr=1e-2).check_state(state)
    _, state_b = _run_zero(mesh, mismatched, params, grads, 1)
    assert int(state_b.bucket_stamp) == 256
    mismatched.check_state(state_b)


def test_zero_step_compiles_to_three_collectives(mesh):
    """The module docstring's performance story: the whole ZeRO step is
    psum_scatter(grads) + [LAMB-only psums] + one all-gather of updated
    params — no hidden extra all-reduces. Counted in the compiled HLO
    (overlap itself is XLA's latency-hiding scheduler; the countable
    invariant is that there is nothing else to overlap-hide)."""
    try:
        from jax._src.lax.parallel import all_gather_invariant  # noqa: F401
    except ImportError:
        pytest.skip("this jax lacks all_gather_invariant; the param "
                    "gather lowers via the documented psum fallback, so "
                    "the 3-collective pattern doesn't apply")
    opt = DistributedFusedAdam(lr=1e-2)
    params = _params()
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    def step(params, grads):
        def inner(params, grads):
            state = opt.init(params)
            return opt.step(grads, state, params)[0]
        gspec = jax.tree_util.tree_map(lambda _: P(), grads)
        return shard_map(inner, mesh=mesh, in_specs=(P(), gspec),
                         out_specs=P())(params, grads)

    txt = jax.jit(step).lower(params, grads).compile().as_text()
    n_rs = txt.count("reduce-scatter(")
    n_ag = txt.count("all-gather(") + txt.count("all-gather-start(")
    n_ar = txt.count("all-reduce(") + txt.count("all-reduce-start(")
    assert n_rs == 1, txt.count("reduce-scatter")
    assert n_ag == 1
    assert n_ar == 0
