"""amp policy + loss scaler tests.

Modeled on the reference L0 amp suite (``reference:tests/L0/run_amp/``):
cast correctness per policy, scaler overflow/growth protocol, skip-step
semantics, checkpoint round-trip of scaler state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


def tree_dtypes(tree):
    return [x.dtype for x in jax.tree_util.tree_leaves(tree)]


class TestPolicy:
    def test_opt_levels(self):
        assert amp.get_policy("O0").compute_dtype == jnp.float32
        o1 = amp.get_policy("O1")
        assert o1.param_dtype == jnp.float32
        assert o1.compute_dtype == jnp.bfloat16
        assert o1.loss_scale is None  # bf16 needs no scaling
        o2_fp16 = amp.get_policy("O2", half_dtype=jnp.float16)
        assert o2_fp16.loss_scale == "dynamic"
        assert o2_fp16.uses_master_weights
        o3 = amp.get_policy("O3")
        assert o3.param_dtype == jnp.bfloat16
        assert not o3.uses_master_weights

    def test_overrides(self):
        p = amp.get_policy("O2", loss_scale=128.0, keep_norms_fp32=False)
        assert p.loss_scale == 128.0
        assert not p.keep_norms_fp32

    def test_bad_level(self):
        with pytest.raises(ValueError):
            amp.get_policy("O4")

    def test_cast_skips_non_float(self):
        tree = {"w": jnp.ones((4, 4), jnp.float32), "step": jnp.asarray(3, jnp.int32)}
        out = amp.cast_to_compute(tree, amp.get_policy("O1"))
        assert out["w"].dtype == jnp.bfloat16
        assert out["step"].dtype == jnp.int32

    def test_with_policy_casts_forward(self):
        pol = amp.get_policy("O2")
        seen = {}

        def fn(params, x):
            seen["param"] = params["w"].dtype
            seen["x"] = x.dtype
            return params["w"] @ x

        wrapped = amp.with_policy(fn, pol)
        out = wrapped({"w": jnp.ones((4, 4), jnp.float32)}, jnp.ones((4,), jnp.float32))
        assert seen["param"] == jnp.bfloat16
        assert seen["x"] == jnp.bfloat16
        assert out.dtype == jnp.bfloat16  # O2 output dtype


class TestLossScale:
    def test_static_noop_update(self):
        ls = amp.StaticLossScale(128.0)
        st = ls.init()
        st2 = ls.update(st, jnp.asarray(False))
        assert float(st2.loss_scale) == 128.0

    def test_dynamic_backoff_and_growth(self):
        ls = amp.DynamicLossScale(init_scale=2.0 ** 16, growth_interval=4)
        st = ls.init()
        # overflow halves
        st = ls.update(st, jnp.asarray(False))
        assert float(st.loss_scale) == 2.0 ** 15
        assert int(st.unskipped) == 0
        # growth_interval clean steps doubles
        for _ in range(4):
            st = ls.update(st, jnp.asarray(True))
        assert float(st.loss_scale) == 2.0 ** 16
        assert int(st.unskipped) == 0

    def test_dynamic_min_clamp(self):
        ls = amp.DynamicLossScale(init_scale=2.0, min_scale=1.0)
        st = ls.init()
        for _ in range(5):
            st = ls.update(st, jnp.asarray(False))
        assert float(st.loss_scale) == 1.0

    def test_dynamic_max_clamp(self):
        ls = amp.DynamicLossScale(init_scale=2.0 ** 24, growth_interval=1,
                                  max_scale=2.0 ** 24)
        st = ls.init()
        st = ls.update(st, jnp.asarray(True))
        assert float(st.loss_scale) == 2.0 ** 24

    def test_unscale_widens(self):
        ls = amp.DynamicLossScale(init_scale=4.0)
        st = ls.init()
        grads = {"w": jnp.full((3,), 8.0, jnp.float16)}
        out = ls.unscale(st, grads)
        assert out["w"].dtype == jnp.float32
        np.testing.assert_allclose(out["w"], 2.0)

    def test_all_finite(self):
        good = {"a": jnp.ones(3), "b": jnp.zeros((2, 2))}
        bad = {"a": jnp.ones(3), "b": jnp.array([1.0, jnp.inf])}
        nan = {"a": jnp.array([jnp.nan])}
        assert bool(amp.all_finite(good))
        assert not bool(amp.all_finite(bad))
        assert not bool(amp.all_finite(nan))
        # int leaves ignored
        assert bool(amp.all_finite({"i": jnp.asarray(2, jnp.int32)}))

    def test_select_tree(self):
        a = {"x": jnp.ones(2)}
        b = {"x": jnp.zeros(2)}
        np.testing.assert_allclose(
            amp.select_tree(jnp.asarray(True), a, b)["x"], 1.0)
        np.testing.assert_allclose(
            amp.select_tree(jnp.asarray(False), a, b)["x"], 0.0)

    def test_make_loss_scale(self):
        assert isinstance(amp.make_loss_scale(None), amp.NoOpLossScale)
        assert isinstance(amp.make_loss_scale("dynamic"), amp.DynamicLossScale)
        s = amp.make_loss_scale(64.0)
        assert isinstance(s, amp.StaticLossScale) and s.init_scale == 64.0


class TestScaledValueAndGrad:
    def test_grads_match_unscaled(self):
        ls = amp.DynamicLossScale(init_scale=2.0 ** 10)
        params = {"w": jnp.arange(4.0)}

        def loss_fn(p, x):
            return jnp.sum(p["w"] * x) ** 2

        x = jnp.ones(4)
        step = amp.scaled_value_and_grad(loss_fn, ls)
        value, aux, grads, finite, st = step(ls.init(), params, x)
        ref_grads = jax.grad(loss_fn)(params, x)
        assert aux is None
        assert bool(finite)
        np.testing.assert_allclose(value, loss_fn(params, x), rtol=1e-6)
        np.testing.assert_allclose(grads["w"], ref_grads["w"], rtol=1e-5)

    def test_overflow_detected_and_scale_lowered(self):
        # fp16 compute with a big scale: scaled loss overflows fp16 range.
        ls = amp.DynamicLossScale(init_scale=2.0 ** 16)
        params = {"w": jnp.full((4,), 1000.0, jnp.float16)}

        def loss_fn(p, x):
            # keep everything fp16 so the scaled backward overflows
            return (p["w"] * x).sum(dtype=jnp.float16).astype(jnp.float32)

        step = amp.scaled_value_and_grad(loss_fn, ls)
        # grads of scaled fp32 loss won't overflow; force it via fp16 cast in fn
        # -> instead simulate: inf grads from inf loss input
        x = jnp.full((4,), 60000.0, jnp.float16)  # w*x overflows fp16
        value, _, grads, finite, st = step(ls.init(), params, x)
        assert not bool(finite)
        assert float(st.loss_scale) == 2.0 ** 15

    def test_has_aux(self):
        ls = amp.StaticLossScale(8.0)

        def loss_fn(p):
            return jnp.sum(p ** 2), {"n": jnp.asarray(1)}

        step = amp.scaled_value_and_grad(loss_fn, ls, has_aux=True)
        value, aux, grads, finite, _ = step(ls.init(), jnp.arange(3.0))
        assert aux["n"] == 1
        np.testing.assert_allclose(grads, 2 * jnp.arange(3.0), rtol=1e-6)

    def test_jittable_and_skip_step(self):
        ls = amp.DynamicLossScale(init_scale=2.0 ** 16)

        def loss_fn(p):
            return jnp.sum(p ** 2)

        step = amp.scaled_value_and_grad(loss_fn, ls)

        @jax.jit
        def train_step(st, params):
            value, _, grads, finite, st = step(st, params)
            new_params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                                params, grads)
            params = amp.select_tree(finite, new_params, params)
            return st, params, value

        st = ls.init()
        params = jnp.arange(4.0)
        st, params, value = train_step(st, params)
        np.testing.assert_allclose(params, jnp.arange(4.0) * 0.8, rtol=1e-6)

    def test_scaler_state_checkpoint_roundtrip(self):
        # the pytree is the state_dict (reference:apex/amp/frontend.py:361-400)
        ls = amp.DynamicLossScale()
        st = ls.init()
        st = ls.update(st, jnp.asarray(False))
        flat, treedef = jax.tree_util.tree_flatten(st)
        restored = jax.tree_util.tree_unflatten(treedef, [np.asarray(x) for x in flat])
        assert float(restored.loss_scale) == float(st.loss_scale)
        assert int(restored.unskipped) == int(st.unskipped)


# ---------------------------------------------------------------------------
# O1 per-op cast lists (reference:apex/amp/lists, tests/L0/run_amp/
# test_basic_casts.py + test_promotion.py)
# ---------------------------------------------------------------------------

class TestO1CastLists:
    def test_half_list_casts_matmul(self):
        from apex_tpu.amp import o1_context
        a = jnp.ones((4, 4), jnp.float32)
        with o1_context(jnp.bfloat16):
            out = jnp.matmul(a, a)
        assert out.dtype == jnp.bfloat16
        # restored on exit
        assert jnp.matmul(a, a).dtype == jnp.float32

    def test_float_list_casts_exp_softmax(self):
        from apex_tpu.amp import o1_context
        x = jnp.ones((8,), jnp.bfloat16)
        with o1_context(jnp.bfloat16):
            assert jnp.exp(x).dtype == jnp.float32
            assert jax.nn.softmax(x).dtype == jnp.float32
            assert jnp.sum(x).dtype == jnp.float32
        assert jnp.exp(x).dtype == jnp.bfloat16

    def test_promote_list_widest_type(self):
        from apex_tpu.amp import o1_context
        lo = jnp.ones((4,), jnp.bfloat16)
        hi = jnp.ones((4,), jnp.float32)
        with o1_context(jnp.bfloat16):
            assert jnp.add(lo, hi).dtype == jnp.float32
            assert jnp.concatenate([lo, hi]).dtype == jnp.float32
            assert jnp.stack([lo, lo]).dtype == jnp.bfloat16

    def test_register_escape_hatch(self):
        import types
        from apex_tpu.amp import o1_context, register_float_function
        mod = types.SimpleNamespace(myop=lambda x: x * 2)
        register_float_function(mod, "myop")
        x = jnp.ones((3,), jnp.bfloat16)
        with o1_context(jnp.bfloat16):
            assert mod.myop(x).dtype == jnp.float32
        assert mod.myop(x).dtype == jnp.bfloat16

    def test_disable_casts(self):
        from apex_tpu.amp import casts_are_enabled, disable_casts, o1_context
        a = jnp.ones((4, 4), jnp.float32)
        with o1_context(jnp.bfloat16):
            with disable_casts():
                assert not casts_are_enabled()
                assert jnp.matmul(a, a).dtype == jnp.float32
            assert casts_are_enabled()
            assert jnp.matmul(a, a).dtype == jnp.bfloat16

    def test_works_under_jit_trace(self):
        from apex_tpu.amp import o1_context
        a = jnp.ones((4, 4), jnp.float32)
        with o1_context(jnp.bfloat16):
            out = jax.jit(lambda a: jnp.matmul(a, a))(a)
        assert out.dtype == jnp.bfloat16

    def test_nested_context_no_double_wrap(self):
        from apex_tpu.amp import o1_context
        a = jnp.ones((4, 4), jnp.float32)
        with o1_context(jnp.bfloat16):
            with o1_context(jnp.bfloat16):
                assert jnp.matmul(a, a).dtype == jnp.bfloat16
            # inner exit must not unwrap the outer patch
            assert jnp.matmul(a, a).dtype == jnp.bfloat16
        assert jnp.matmul(a, a).dtype == jnp.float32
