"""Deprecated-surface parity: fp16_utils works as a thin adapter; RNN/
reparameterization are documented stubs (SURVEY §7.7). pyprof (PR 6) and
multiproc (PR 13) graduated to real packages; their era-appropriate stub
surfaces are pinned here."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import FusedAdam


def test_fp16_optimizer_trains_and_skips_overflow():
    from apex_tpu.fp16_utils import FP16_Optimizer

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16, 16) * 0.3, jnp.float16)}
    x = jnp.asarray(rng.randn(32, 16), jnp.float16)
    y = jnp.asarray(rng.randn(32, 16), jnp.float32)
    opt = FP16_Optimizer(FusedAdam(lr=1e-2), dynamic_loss_scale=True,
                         init_scale=2.0 ** 8, growth_interval=4)
    state = opt.init(params)

    @jax.jit
    def step(params, state, poison):
        def loss_fn(p):
            out = (x @ p["w"]).astype(jnp.float32)
            return jnp.mean((out - y) ** 2) * (1.0 + poison)
        loss = loss_fn(params)
        grads = jax.grad(lambda p: opt.scale_loss(state, loss_fn(p)))(params)
        params, state = opt.step(grads, state, params)
        return params, state, loss

    losses = []
    for i in range(15):
        poison = jnp.asarray(np.inf if i == 4 else 0.0, jnp.float32)
        before = np.asarray(params["w"])
        params, state, loss = step(params, state, poison)
        if i == 4:
            np.testing.assert_array_equal(np.asarray(params["w"]), before)
        else:
            losses.append(float(loss))
    assert params["w"].dtype == jnp.float16
    assert state[0]["w"].dtype == jnp.float32  # fp32 masters
    assert losses[-1] < losses[0] * 0.8


def test_fp16_util_helpers():
    from apex_tpu.fp16_utils import (convert_network,
                                     master_params_to_model_params,
                                     network_to_half, prep_param_lists)

    params = {"w": jnp.ones((4, 4), jnp.float32), "step": jnp.asarray(3)}
    half = network_to_half(params)
    assert half["w"].dtype == jnp.float16 and half["step"].dtype == jnp.int32
    assert convert_network(params, jnp.bfloat16)["w"].dtype == jnp.bfloat16
    model, master = prep_param_lists(half)
    assert master["w"].dtype == jnp.float32
    synced = master_params_to_model_params(model, master)
    assert synced["w"].dtype == jnp.float16


def test_stub_packages_raise_with_migration_pointers():
    import apex_tpu

    for mod_name, needle in [("reparameterization", "WeightNorm")]:
        mod = getattr(apex_tpu, mod_name)
        with pytest.raises(NotImplementedError) as e:
            mod.anything
        assert needle in str(e.value)

    # multiproc graduated from stub to the real multi-host bootstrap:
    # its CLI is now a launcher (argparse: no args -> usage exit 2); the
    # worker-side bootstrap and the env protocol live in
    # tests/test_multiproc.py
    from apex_tpu.parallel import multiproc
    with pytest.raises(SystemExit) as e:
        multiproc.main([])
    assert e.value.code == 2


def test_pyprof_nvtx_era_names_keep_the_stub_contract():
    """pyprof graduated to a real package in round 6, but the NVTX-era
    surface the old stub documented (`nvtx`, `prof`, `parse`) must keep
    raising NotImplementedError with a pointer into the new
    annotate -> trace -> attribute API."""
    from apex_tpu import pyprof

    for name, needle in [("nvtx", "annotate"),
                         ("prof", "attribute"),
                         ("parse", "region_times_from_trace_dir")]:
        with pytest.raises(NotImplementedError) as e:
            getattr(pyprof, name)
        msg = str(e.value)
        assert needle in msg and "annotate" in msg, msg
    # anything else is a plain missing attribute, not a stub raise
    with pytest.raises(AttributeError):
        pyprof.definitely_not_an_api


def test_pyprof_new_surface_is_real():
    from apex_tpu import pyprof

    # the annotate stage IS jax.named_scope
    assert pyprof.annotate is jax.named_scope
    for name in ("attribute", "model_program", "jaxpr_of",
                 "region_times_from_spans", "region_times_from_trace_dir"):
        assert callable(getattr(pyprof, name)), name
    assert pyprof.DEFAULT_REGIONS and "gpt_attention" in \
        pyprof.DEFAULT_REGIONS


def test_rnn_package_is_real():
    # apex_tpu.RNN graduated from stub to a working package in round 4;
    # its factory surface matches reference:apex/RNN/models.py:19-53.
    from apex_tpu import RNN

    for name in ("LSTM", "GRU", "ReLU", "Tanh", "mLSTM"):
        assert callable(getattr(RNN, name))
