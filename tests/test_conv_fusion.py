"""conv+bias(+relu/mask/scale) parity vs torch
(``reference:apex/contrib/test/conv_bias_relu/test_conv_bias_relu.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch
import torch.nn.functional as F

from apex_tpu.ops.conv_fusion import (conv_bias, conv_bias_mask_relu,
                                      conv_bias_relu,
                                      conv_frozen_scale_bias_relu)


def _data(cin=4, cout=8, k=3, n=2, s=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, s, s, cin).astype(np.float32)
    w = rng.randn(k, k, cin, cout).astype(np.float32) * 0.1
    b = rng.randn(cout).astype(np.float32)
    return x, w, b


def _torch_conv(x, w, b, stride, padding):
    tx = torch.tensor(x).permute(0, 3, 1, 2)
    tw = torch.tensor(w).permute(3, 2, 0, 1)
    out = F.conv2d(tx, tw, torch.tensor(b), stride=stride, padding=padding)
    return out.permute(0, 2, 3, 1).numpy()


def test_conv_bias_and_relu_match_torch():
    x, w, b = _data()
    for stride, pad in [(1, 1), (2, 0)]:
        ref = _torch_conv(x, w, b, stride, pad)
        np.testing.assert_allclose(
            np.asarray(conv_bias(jnp.asarray(x), jnp.asarray(w),
                                 jnp.asarray(b), stride, pad)),
            ref, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(conv_bias_relu(jnp.asarray(x), jnp.asarray(w),
                                      jnp.asarray(b), stride, pad)),
            np.maximum(ref, 0), rtol=2e-5, atol=2e-5)


def test_conv_bias_mask_relu_and_frozen_scale():
    x, w, b = _data(seed=1)
    ref = _torch_conv(x, w, b, 1, 1)
    mask = (np.random.RandomState(2).rand(*ref.shape) > 0.5).astype(
        np.float32)
    np.testing.assert_allclose(
        np.asarray(conv_bias_mask_relu(jnp.asarray(x), jnp.asarray(w),
                                       jnp.asarray(b), jnp.asarray(mask),
                                       1, 1)),
        np.maximum(ref * mask, 0), rtol=2e-5, atol=2e-5)

    scale = np.random.RandomState(3).rand(8).astype(np.float32) + 0.5
    ref_nb = _torch_conv(x, w, np.zeros(8, np.float32), 1, 1)
    np.testing.assert_allclose(
        np.asarray(conv_frozen_scale_bias_relu(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(scale),
            jnp.asarray(b), 1, 1)),
        np.maximum(ref_nb * scale + b, 0), rtol=2e-5, atol=2e-5)


def test_grads_flow():
    x, w, b = _data(seed=4)
    g = jax.grad(lambda w, b: jnp.sum(conv_bias_relu(
        jnp.asarray(x), w, b, 1, 1) ** 2), argnums=(0, 1))(
            jnp.asarray(w), jnp.asarray(b))
    for leaf in g:
        assert np.isfinite(np.asarray(leaf)).all()
