"""Serving resilience layer (docs/SERVING.md "Resilience"): admission
control + load shedding, deadlines + cancel, poison-slot quarantine,
graceful drain + zero-recompile hot weight swap, SLO brownout, and the
deterministic serving chaos plan — each contract proven, plus the
zero-cost-off assertion (three AOT programs byte-identical with every
feature off)."""

import json
import time

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.elastic.faults import FaultPlan
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.observability.registry import MetricsRegistry
from apex_tpu.observability.reqtrace import RequestTrace
from apex_tpu.observability.slo import SLOTarget, SLOTracker
from apex_tpu.serving import (BrownoutPolicy, CheckpointWatcher,
                              Rejection, Request, ServingEngine,
                              SlotScheduler, watch_checkpoints)


@pytest.fixture(scope="module")
def model_params():
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=64)
    model = GPTModel(cfg)
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def engine(model_params):
    """Shared plain engine — tests must drain fully (and never swap its
    params) so slot/cache state is clean for the next one."""
    model, params = model_params
    return ServingEngine(model, params, max_seqs=2, max_len=32,
                         prefill_len=8)


@pytest.fixture(scope="module")
def qengine(model_params):
    """Shared quarantine engine (the poison check compiled in)."""
    model, params = model_params
    return ServingEngine(model, params, max_seqs=2, max_len=32,
                         prefill_len=8, quarantine=True)


def _sched(engine, **kw):
    reg = MetricsRegistry()
    return SlotScheduler(engine, registry=reg, **kw), reg


# ---------------------------------------------------------------------------
# admission control & load shedding
# ---------------------------------------------------------------------------

class TestAdmissionControl:
    def test_queue_full_typed_rejection(self, engine):
        sched, reg = _sched(engine, max_queue=2)
        ids = [sched.submit(Request(prompt=[1 + i], max_new_tokens=2))
               for i in range(5)]
        admitted = [r for r in ids if isinstance(r, int)]
        rejected = [r for r in ids if isinstance(r, Rejection)]
        assert len(admitted) == 2 and len(rejected) == 3
        for r in rejected:
            assert r.reason == "queue_full" and not r  # falsy by design
        assert len(sched.queue) == 2  # the bound held
        assert reg.snapshot()["serve/rejected"] == 3.0
        # the admitted requests still complete normally
        out = sched.run([])
        assert sorted(out) == sorted(admitted)

    def test_rejection_reason_vocabulary_closed(self):
        with pytest.raises(ValueError, match="reason"):
            Rejection("overloaded")

    def test_max_queue_validated(self, engine):
        with pytest.raises(ValueError, match="max_queue"):
            SlotScheduler(engine, registry=MetricsRegistry(), max_queue=0)

    def test_overload_2x_bounded_queue_and_goodput_ab(self, engine):
        """The overload contract (same-session A/B): at 2x sustained
        oversubmission with max_queue set, queue depth stays bounded,
        rejections are typed, and the in-SLO goodput of ADMITTED
        requests stays within 2x of the unloaded run's."""
        slo = [SLOTarget("e2e_ms", 95, 60000.0)]  # generous: CPU timing

        def tracker():
            return SLOTracker(slo, registry=MetricsRegistry(),
                              on_violation="skip")

        # unloaded: fewer requests than slots-worth of queue, no bound
        t_unloaded = tracker()
        sched, _ = _sched(engine, slo=t_unloaded)
        sched.run([Request(prompt=[1 + i], max_new_tokens=2)
                   for i in range(4)])
        unloaded_goodput = t_unloaded.goodput()

        # 2x oversubmission: a 3-token request holds its slot for 2
        # decode steps, so the 2-slot grid completes ~1 request/step —
        # and every step submits 2 fresh ones against a max_queue=2
        # bound: sustained offered load is 2x capacity
        t_loaded = tracker()
        sched, reg = _sched(engine, max_queue=2, slo=t_loaded)
        rejections, max_depth = [], 0
        for i in range(30):
            for j in range(2):
                r = sched.submit(Request(prompt=[1 + (i + j) % 90],
                                         max_new_tokens=3))
                if isinstance(r, Rejection):
                    rejections.append(r)
            sched.step()
            max_depth = max(max_depth, len(sched.queue))
        sched.run([])  # drain the tail
        assert max_depth <= 2, "queue depth exceeded max_queue"
        assert rejections and all(r.reason == "queue_full"
                                  for r in rejections)
        assert reg.snapshot()["serve/rejected"] == float(len(rejections))
        # admitted requests' goodput within a factor 2 of unloaded
        assert t_loaded.goodput() >= 0.5 * unloaded_goodput

    def test_run_paces_submissions_at_the_queue_bound(self, engine):
        """A closed batch knows its remaining work: run() holds
        queue_full'd requests host-side and resubmits as the queue
        drains — every request is eventually served while the bound
        holds throughout (silently dropping work a later step could
        serve would be a shedding decision the caller never made)."""
        sched, reg = _sched(engine, max_queue=1)
        out = sched.run([Request(prompt=[1 + i], max_new_tokens=2)
                         for i in range(4)])
        assert sorted(out) == [0, 1, 2, 3]
        assert all(c.finish_reason == "length" for c in out.values())
        # paced retries are NOT refused submissions: the counter an
        # operator alerts on must stay silent on a healthy closed batch
        assert reg.snapshot().get("serve/rejected", 0.0) == 0.0

    def test_run_drops_shed_requests(self, engine):
        """shed (brownout) rejections are final even inside run() —
        pacing applies only to queue_full backpressure."""
        tracker = _hot_tracker()
        sched, reg = _sched(engine,
                            brownout=BrownoutPolicy(tracker, shed=True))
        out = sched.run([Request(prompt=[1], max_new_tokens=2)])
        assert out == {}
        assert reg.snapshot()["serve/shed"] == 1.0


# ---------------------------------------------------------------------------
# deadlines + cancel
# ---------------------------------------------------------------------------

class TestDeadlines:
    def test_queued_expiry_never_spends_a_slot(self, engine):
        sched, reg = _sched(engine)
        for i in range(2):
            sched.submit(Request(prompt=[1 + i], max_new_tokens=4))
        rid = sched.submit(Request(prompt=[9], max_new_tokens=4,
                                   deadline_ms=1e-3))
        time.sleep(0.005)
        out = sched.run([])
        assert out[rid].finish_reason == "expired"
        assert out[rid].tokens == []
        snap = reg.snapshot()
        assert snap["serve/expired"] == 1.0
        assert snap["serve/admitted"] == 2.0  # the expired one never did

    def test_mid_flight_expiry_releases_slot(self, engine):
        sched, reg = _sched(engine)
        rid = sched.submit(Request(prompt=[1], max_new_tokens=500,
                                   deadline_ms=30.0))
        sched.step()  # admitted, first token sampled
        assert sched.active
        time.sleep(0.05)
        while sched.pending:
            sched.step()
        out = {c.request_id: c for c in sched.completed}
        assert out[rid].finish_reason == "expired"
        assert len(out[rid].tokens) >= 1  # partial output delivered
        assert not sched.active and sorted(sched.free) == [0, 1]
        np.testing.assert_array_equal(
            np.asarray(sched.engine.cache.lengths), [0, 0])
        assert reg.snapshot()["serve/expired"] == 1.0

    def test_default_deadline_applies_when_request_sets_none(self, engine):
        sched, reg = _sched(engine, default_deadline_ms=1e-3)
        sched.submit(Request(prompt=[1], max_new_tokens=2))
        # a per-request deadline overrides the default
        ok = sched.submit(Request(prompt=[2], max_new_tokens=2,
                                  deadline_ms=60000.0))
        time.sleep(0.005)
        out = sched.run([])
        reasons = {k: v.finish_reason for k, v in out.items()}
        assert reasons[0] == "expired" and reasons[ok] == "length"

    def test_expired_requests_hurt_goodput(self, engine):
        """A queued expiry has NO measured ttft/tpot and a tiny e2e —
        it would sail under every latency target; the tracker must count
        server-side failure retirements against goodput unconditionally
        (FAILED_REASONS), or shedding the queue would READ as serving
        well."""
        tracker = SLOTracker([SLOTarget("e2e_ms", 95, 60000.0)],
                             registry=MetricsRegistry(),
                             on_violation="skip")
        sched, _ = _sched(engine, slo=tracker)
        for i in range(2):
            sched.submit(Request(prompt=[1 + i], max_new_tokens=2))
        sched.submit(Request(prompt=[9], max_new_tokens=2,
                             deadline_ms=1e-3))
        time.sleep(0.005)
        sched.run([])
        assert tracker.goodput() == pytest.approx(2.0 / 3.0)

    def test_cancel_queued_and_mid_flight(self, engine):
        sched, reg = _sched(engine)
        a = sched.submit(Request(prompt=[1], max_new_tokens=50))
        b = sched.submit(Request(prompt=[2], max_new_tokens=3))
        c = sched.submit(Request(prompt=[3], max_new_tokens=3))
        sched.step()  # a, b admitted; c queued
        assert sched.cancel(c)   # queued cancel
        assert sched.cancel(a)   # mid-flight cancel — slot freed
        assert not sched.cancel(a)   # idempotent: already gone
        assert not sched.cancel(999)  # unknown id
        sched.run([])
        out = {c_.request_id: c_ for c_ in sched.completed}
        reasons = {k: v.finish_reason for k, v in out.items()}
        assert reasons == {a: "cancelled", b: "length", c: "cancelled"}
        assert out[c].tokens == []
        assert reg.snapshot()["serve/cancelled"] == 2.0


class TestSubmitValidation:
    def test_nonpositive_deadline_raises(self, engine):
        sched, _ = _sched(engine)
        for bad in (0.0, -5.0):
            with pytest.raises(ValueError, match="deadline_ms"):
                sched.submit(Request(prompt=[1], deadline_ms=bad))
        assert sched.pending == 0

    def test_duplicate_in_flight_id_raises_then_reusable(self, engine):
        sched, _ = _sched(engine)
        sched.submit(Request(prompt=[1], max_new_tokens=2, request_id=7))
        with pytest.raises(ValueError, match="already in flight"):
            sched.submit(Request(prompt=[2], request_id=7))
        assert sched.pending == 1
        out = sched.run([])
        assert out[7].finish_reason == "length"
        # after completion the id is free again (replay/retry semantics)
        out = sched.run([Request(prompt=[3], max_new_tokens=2,
                                 request_id=7)])
        assert sorted(out) == [7]

    def test_default_deadline_validated(self, engine):
        with pytest.raises(ValueError, match="default_deadline_ms"):
            SlotScheduler(engine, registry=MetricsRegistry(),
                          default_deadline_ms=0.0)


# ---------------------------------------------------------------------------
# poison-slot quarantine
# ---------------------------------------------------------------------------

class TestQuarantine:
    def test_poison_retires_only_offending_slot(self, qengine, tmp_path):
        """The isolation contract: the injected poison step retires
        exactly one slot with finish_reason "poisoned"; every other
        request's greedy stream is identical to the fault-free run."""
        reqs = [Request(prompt=[5, 6], max_new_tokens=8),
                Request(prompt=[7, 8], max_new_tokens=8)]

        def run(plan, dump_dir):
            sched, reg = _sched(qengine, fault_plan=plan,
                                dump_dir=str(dump_dir))
            out = sched.run([Request(prompt=list(r.prompt),
                                     max_new_tokens=r.max_new_tokens)
                             for r in reqs])
            return out, reg, sched

        clean, _, _ = run(None, tmp_path / "clean")
        plan = FaultPlan(poison_logits={3: 0})
        faulted, reg, sched = run(plan, tmp_path / "faulted")

        assert faulted[0].finish_reason == "poisoned"
        # tokens up to the poison step were delivered; the NaN-step
        # token was discarded (prefill token + 2 decode ticks)
        assert faulted[0].tokens == clean[0].tokens[:3]
        # the neighbor's stream is IDENTICAL to the fault-free run
        assert faulted[1].finish_reason == "length"
        assert faulted[1].tokens == clean[1].tokens
        assert reg.snapshot()["serve/poisoned"] == 1.0
        # the slot was released (cursor zeroed) like any retirement
        np.testing.assert_array_equal(
            np.asarray(qengine.cache.lengths), [0, 0])

    def test_poison_writes_strict_json_flight_record(self, qengine,
                                                     tmp_path):
        trace = RequestTrace(capacity=8)
        sched = SlotScheduler(qengine, registry=MetricsRegistry(),
                              trace=trace,
                              fault_plan=FaultPlan(poison_logits={2: 1}),
                              dump_dir=str(tmp_path))
        sched.run([Request(prompt=[3, 4], max_new_tokens=6),
                   Request(prompt=[5, 6], max_new_tokens=6)])
        assert len(sched.poison_dumps) == 1
        with open(sched.poison_dumps[0]) as f:
            doc = json.load(f)  # strict JSON by construction
        assert doc["config"]["finish_reason"] == "poisoned"
        assert doc["config"]["slot"] == 1
        recs = doc["requests"]
        assert any(r["finish_reason"] == "poisoned" for r in recs)

    def test_poison_plan_refused_on_plain_engine(self, engine):
        with pytest.raises(ValueError, match="quarantine"):
            SlotScheduler(engine, registry=MetricsRegistry(),
                          fault_plan=FaultPlan(poison_logits={1: 0}))
        with pytest.raises(ValueError, match="quarantine"):
            engine.decode(np.zeros(2, np.int32), np.zeros(2, np.float32),
                          poison=np.zeros(2, np.float32))

    def test_quarantine_engine_serves_identically_unpoisoned(
            self, engine, qengine):
        """The quarantine check observes, never perturbs: an unpoisoned
        run on the quarantine engine produces the same greedy streams as
        the plain engine."""
        reqs = [Request(prompt=[11, 12, 13], max_new_tokens=5),
                Request(prompt=[14], max_new_tokens=5)]
        out_plain = SlotScheduler(engine, registry=MetricsRegistry()).run(
            [Request(prompt=list(r.prompt),
                     max_new_tokens=r.max_new_tokens) for r in reqs])
        out_q = SlotScheduler(qengine, registry=MetricsRegistry()).run(
            [Request(prompt=list(r.prompt),
                     max_new_tokens=r.max_new_tokens) for r in reqs])
        for rid in out_plain:
            assert out_plain[rid].tokens == out_q[rid].tokens


# ---------------------------------------------------------------------------
# zero-cost off + zero-recompile contracts
# ---------------------------------------------------------------------------

class TestZeroCostOff:
    def test_programs_byte_identical_with_resilience_off(
            self, model_params, engine):
        """The established zero-cost idiom: resilience features OFF
        (quarantine off at the engine, no scheduler knobs) leaves all
        three AOT programs byte-identical to a freshly-built baseline
        engine's."""
        model, params = model_params
        fresh = ServingEngine(model, params, max_seqs=2, max_len=32,
                              prefill_len=8)
        for a, b in ((engine.prefill_compiled, fresh.prefill_compiled),
                     (engine.decode_compiled, fresh.decode_compiled),
                     (engine.release_compiled, fresh.release_compiled)):
            assert a.as_text() == b.as_text()

    def test_host_side_knobs_leave_programs_untouched(self, model_params,
                                                      engine):
        """max_queue / deadlines / brownout / flood plans are pure host
        policy: a scheduler wired with all of them drives byte-identical
        programs with zero recompiles."""
        model, params = model_params
        wired_eng = ServingEngine(model, params, max_seqs=2, max_len=32,
                                  prefill_len=8)
        tracker = SLOTracker([SLOTarget("ttft_ms", 95, 60000.0)],
                             registry=MetricsRegistry(),
                             on_violation="skip")
        sched = SlotScheduler(
            wired_eng, registry=MetricsRegistry(), slo=tracker,
            max_queue=8, default_deadline_ms=60000.0,
            brownout=BrownoutPolicy(tracker, cap_max_new_tokens=64),
            fault_plan=FaultPlan(flood={2: 1}))
        out = sched.run([Request(prompt=[1 + i], max_new_tokens=3)
                         for i in range(3)], no_recompile=True)
        assert sorted(out) == [0, 1, 2]
        for a, b in ((engine.prefill_compiled, wired_eng.prefill_compiled),
                     (engine.decode_compiled, wired_eng.decode_compiled),
                     (engine.release_compiled,
                      wired_eng.release_compiled)):
            assert a.as_text() == b.as_text()

    def test_quarantine_differs_only_in_decode(self, engine, qengine):
        assert (engine.prefill_compiled.as_text()
                == qengine.prefill_compiled.as_text())
        assert (engine.release_compiled.as_text()
                == qengine.release_compiled.as_text())
        assert (engine.decode_compiled.as_text()
                != qengine.decode_compiled.as_text())

    def test_poison_injection_never_recompiles(self, qengine):
        """Injecting (and clearing) poison is an array-argument change on
        the already-compiled quarantine program — flat compile counters
        across a run that poisons mid-flight."""
        sched = SlotScheduler(qengine, registry=MetricsRegistry(),
                              fault_plan=FaultPlan(poison_logits={2: 0}),
                              dump_dir="/tmp")
        out = sched.run([Request(prompt=[2, 3], max_new_tokens=6),
                         Request(prompt=[4, 5], max_new_tokens=6)],
                        no_recompile=True)
        assert out[0].finish_reason == "poisoned"
        assert out[1].finish_reason == "length"


# ---------------------------------------------------------------------------
# graceful drain + hot weight swap
# ---------------------------------------------------------------------------

class TestDrain:
    def test_drain_finishes_in_flight_keeps_queued(self, engine):
        sched, reg = _sched(engine)
        for i in range(4):
            sched.submit(Request(prompt=[1 + i], max_new_tokens=4))
        sched.step()  # 2 admitted, 2 queued
        done = sched.drain()
        assert sorted(done) == [0, 1]
        assert all(c.finish_reason == "length" for c in done.values())
        assert len(sched.queue) == 2  # queued survive for after the swap
        assert reg.snapshot()["serve/drains"] == 1.0
        # admission resumes after the drain returns
        assert isinstance(sched.submit(Request(prompt=[9],
                                               max_new_tokens=2)), int)
        sched.run([])  # leave the shared engine clean

    def test_submit_during_drain_rejected(self, engine, monkeypatch):
        sched, reg = _sched(engine)
        sched.submit(Request(prompt=[1], max_new_tokens=3))
        sched.step()
        seen = {}

        # observe the draining flag from inside the drain loop via the
        # step path itself
        orig_step = sched.step

        def probing_step():
            r = sched.submit(Request(prompt=[5], max_new_tokens=1))
            seen["rejection"] = r
            return orig_step()

        monkeypatch.setattr(sched, "step", probing_step)
        sched.drain()
        assert isinstance(seen["rejection"], Rejection)
        assert seen["rejection"].reason == "draining"
        assert reg.snapshot()["serve/rejected"] >= 1.0

    def test_drain_deadline_expires_leftovers(self, engine):
        """A drain running out of budget is the SERVER dropping accepted
        work: leftovers retire "expired" (a FAILED_REASONS member, so a
        lossy rollover shows up in goodput), not "cancelled" (which
        means the user walked away)."""
        tracker = SLOTracker([SLOTarget("e2e_ms", 95, 60000.0)],
                             registry=MetricsRegistry(),
                             on_violation="skip")
        sched, reg = _sched(engine, slo=tracker)
        rid = sched.submit(Request(prompt=[1], max_new_tokens=100000))
        sched.step()
        done = sched.drain(deadline_s=0.0)  # never finishes in time
        assert done[rid].finish_reason == "expired"
        assert not sched.active and sorted(sched.free) == [0, 1]
        assert reg.snapshot()["serve/expired"] == 1.0
        assert tracker.goodput() == 0.0  # the lossy drain is visible


class TestHotSwap:
    def _engine(self, model_params, **kw):
        model, params = model_params
        return ServingEngine(model, params, max_seqs=2, max_len=32,
                             prefill_len=8, **kw), model, params

    def test_swap_mid_run_completes_in_flight_and_changes_outputs(
            self, model_params):
        """The hot-swap contract: swap_params mid-loop completes
        in-flight requests, subsequent outputs come from the NEW
        weights, and the compile-storm counters stay flat (zero
        recompiles) with donation re-linted on the swap."""
        from apex_tpu.analysis.program import recompile_guard

        eng, model, params = self._engine(model_params)
        # a fresh init, not a scalar multiple of the old weights
        # (layernorm makes uniformly-scaled params nearly
        # argmax-invariant) — and the probe prompt is SEARCHED for one
        # where the two weight sets disagree on the first greedy token,
        # because a tiny random model can decode the same degenerate
        # repetition stream under unrelated inits
        new_params = model.init(jax.random.PRNGKey(123))

        def nxt(p, toks):
            return int(np.argmax(np.asarray(
                model(p, jnp.asarray([toks]))[0, -1])))

        prompt = next(t for t in ([1 + i, 2 + i, 3 + i]
                                  for i in range(60))
                      if nxt(params, t) != nxt(new_params, t))
        sched, reg = _sched(eng)

        # reference streams for the probe prompt under each weight set
        ref_old = SlotScheduler(eng, registry=MetricsRegistry()).run(
            [Request(prompt=list(prompt), max_new_tokens=6)])[0].tokens
        eng2, _, _ = self._engine(model_params)
        eng2.swap_params(new_params)
        ref_new = SlotScheduler(eng2, registry=MetricsRegistry()).run(
            [Request(prompt=list(prompt), max_new_tokens=6)])[0].tokens
        assert ref_old != ref_new  # guaranteed by the probe search

        mid = sched.submit(Request(prompt=[7, 8], max_new_tokens=12))
        sched.step()
        sched.step()
        with recompile_guard("hot swap") as guard:
            sched.step()
            guard.rebase()  # host paths warm; the swap must stay flat
            sched.swap_params(new_params)
            while sched.pending:
                sched.step()
            post = sched.run([Request(prompt=list(prompt),
                                      max_new_tokens=6, request_id=50)])
        out = {c.request_id: c for c in sched.completed}
        # the in-flight request completed across the swap
        assert out[mid].finish_reason == "length"
        assert len(out[mid].tokens) == 12
        # a post-swap request decodes the NEW weights' stream exactly
        assert post[50].tokens == ref_new
        assert reg.snapshot()["serve/swaps"] == 1.0
        assert eng.swaps == 1

    def test_swap_shape_and_structure_mismatches_refused(
            self, model_params):
        eng, model, params = self._engine(model_params)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        with pytest.raises(ValueError, match="structure"):
            eng.swap_params(leaves)  # a list is not the params tree
        bad = jax.tree_util.tree_unflatten(
            treedef, [jnp.zeros((3, 3), jnp.float32) for _ in leaves])
        with pytest.raises(ValueError, match="never retrace"):
            eng.swap_params(bad)
        # the engine still serves with its original weights
        out = SlotScheduler(eng, registry=MetricsRegistry()).run(
            [Request(prompt=[1], max_new_tokens=2)])
        assert out[0].finish_reason == "length"


class TestCheckpointWatcher:
    def test_rolls_onto_latest_committed_only(self, model_params,
                                              tmp_path):
        from apex_tpu.checkpoint import save_checkpoint

        eng, model, params = TestHotSwap()._engine(model_params)
        reg = MetricsRegistry()
        run_dir = str(tmp_path)
        watcher = CheckpointWatcher(eng, run_dir, registry=reg)
        assert watcher.poll() is None  # no checkpoint yet: keep serving

        p1 = jax.tree_util.tree_map(lambda x: x * 1.5, params)
        save_checkpoint(run_dir, p1, 1)
        assert watcher.poll() == 1
        assert watcher.poll() is None  # nothing new
        np.testing.assert_allclose(
            np.asarray(jax.tree_util.tree_leaves(eng.params)[0]),
            np.asarray(jax.tree_util.tree_leaves(p1)[0]), rtol=1e-6)

        # a torn step (no COMMITTED marker) is invisible; the watcher
        # rolls onto the newest COMMITTED step beneath it
        p2 = jax.tree_util.tree_map(lambda x: x * 0.5, params)
        save_checkpoint(run_dir, p2, 2)
        (tmp_path / "step_00000003").mkdir()
        assert watcher.poll() == 2
        assert reg.snapshot()["serve/swaps"] == 2.0

    def test_watch_checkpoints_polls_immediately(self, model_params,
                                                 tmp_path):
        from apex_tpu.checkpoint import save_checkpoint

        eng, model, params = TestHotSwap()._engine(model_params)
        p1 = jax.tree_util.tree_map(lambda x: x + 0.25, params)
        save_checkpoint(str(tmp_path), p1, 5)
        watcher = watch_checkpoints(eng, str(tmp_path))
        assert watcher.step == 5


# ---------------------------------------------------------------------------
# SLO-driven brownout
# ---------------------------------------------------------------------------

def _hot_tracker(threshold_ms=1.0, n=16):
    """A tracker whose window is saturated with over-threshold e2e
    observations — burn rate far above 1."""
    from apex_tpu.observability.reqtrace import RequestRecord

    tracker = SLOTracker([SLOTarget("e2e_ms", 95, threshold_ms)],
                         registry=MetricsRegistry(), on_violation="skip")
    for i in range(n):
        rec = RequestRecord(request_id=i, prompt_len=1, submit_t=0.0)
        rec.retire_t = 10.0  # e2e = 10000 ms >> threshold
        tracker.observe(rec)
    return tracker


class TestBrownout:
    def test_shed_on_burn_rate_over_threshold(self, engine):
        tracker = _hot_tracker()
        assert tracker.max_burn_rate() > 1.0
        sched, reg = _sched(engine,
                            brownout=BrownoutPolicy(tracker, shed=True))
        r = sched.submit(Request(prompt=[1], max_new_tokens=4))
        assert isinstance(r, Rejection) and r.reason == "shed"
        snap = reg.snapshot()
        assert snap["serve/shed"] == 1.0
        assert snap["serve/brownout"] == 1.0

    def test_cap_max_new_tokens_instead_of_shedding(self, engine):
        tracker = _hot_tracker()
        policy = BrownoutPolicy(tracker, shed=False, cap_max_new_tokens=2)
        sched, reg = _sched(engine, brownout=policy)
        rid = sched.submit(Request(prompt=[1], max_new_tokens=50))
        assert isinstance(rid, int)
        out = sched.run([])
        # graceful degradation: served, but short
        assert out[rid].finish_reason == "length"
        assert len(out[rid].tokens) == 2

    def test_cold_window_never_engages(self, engine):
        tracker = SLOTracker([SLOTarget("e2e_ms", 95, 1.0)],
                             registry=MetricsRegistry(),
                             on_violation="skip")
        sched, reg = _sched(engine,
                            brownout=BrownoutPolicy(tracker, shed=True))
        rid = sched.submit(Request(prompt=[1], max_new_tokens=2))
        assert isinstance(rid, int)  # NaN burn (empty window) admits
        assert reg.snapshot()["serve/brownout"] == 0.0
        sched.run([])

    def test_policy_validation(self):
        tracker = _hot_tracker()
        with pytest.raises(ValueError, match="burn_threshold"):
            BrownoutPolicy(tracker, burn_threshold=0.0)
        with pytest.raises(ValueError, match="cap_max_new_tokens"):
            BrownoutPolicy(tracker, cap_max_new_tokens=0)
        with pytest.raises(ValueError, match="nothing"):
            BrownoutPolicy(tracker, shed=False)


# ---------------------------------------------------------------------------
# exception safety
# ---------------------------------------------------------------------------

class TestExceptionSafety:
    def test_decode_fault_retires_in_flight_and_reraises(self, engine,
                                                         monkeypatch):
        sched, reg = _sched(engine)
        a = sched.submit(Request(prompt=[1], max_new_tokens=9))
        b = sched.submit(Request(prompt=[2], max_new_tokens=9))
        sched.step()
        assert len(sched.active) == 2

        def boom(*args, **kw):
            raise RuntimeError("injected decode fault")

        monkeypatch.setattr(engine, "decode", boom)
        with pytest.raises(RuntimeError, match="injected decode fault"):
            sched.step()
        # nothing stranded: records retired, slots released, loop usable
        assert not sched.active and sorted(sched.free) == [0, 1]
        out = {c.request_id: c for c in sched.completed}
        assert out[a].finish_reason == "error"
        assert out[b].finish_reason == "error"
        assert len(out[a].tokens) >= 1  # partial output still delivered
        assert reg.snapshot()["serve/errors"] == 2.0
        monkeypatch.undo()
        post = sched.run([Request(prompt=[3], max_new_tokens=2)])
        assert len(post) == 1

    def test_prefill_fault_retires_popped_request(self, engine,
                                                  monkeypatch):
        sched, reg = _sched(engine)
        rid = sched.submit(Request(prompt=[1], max_new_tokens=4))

        def boom(*args, **kw):
            raise RuntimeError("injected prefill fault")

        monkeypatch.setattr(engine, "prefill", boom)
        with pytest.raises(RuntimeError, match="injected prefill fault"):
            sched.step()
        assert sorted(sched.free) == [0, 1]  # the popped slot came back
        out = {c.request_id: c for c in sched.completed}
        assert out[rid].finish_reason == "error"
        assert reg.snapshot()["serve/errors"] == 1.0
        monkeypatch.undo()
        assert len(sched.run([Request(prompt=[2],
                                      max_new_tokens=2)])) == 1


# ---------------------------------------------------------------------------
# FaultPlan serving faults + the chaos run
# ---------------------------------------------------------------------------

class TestServingFaultPlan:
    def test_json_round_trip(self):
        plan = FaultPlan(poison_logits={4: 1}, slow_decode_s=0.25,
                         flood={2: 6}, seed=9)
        assert FaultPlan.from_json(plan.to_json()) == plan

    def test_sample_serving_deterministic_and_bounded(self):
        a = FaultPlan.sample_serving(23, 10, max_slots=2)
        b = FaultPlan.sample_serving(23, 10, max_slots=2)
        assert a == b and a.seed == 23
        for seed in range(20):
            p = FaultPlan.sample_serving(seed, 12, max_slots=4,
                                         flood_n=3)
            (fstep, fn), = p.flood.items()
            (pstep, pslot), = p.poison_logits.items()
            assert 1 <= fstep < 3 and fn == 3
            assert 6 <= pstep < 12 and 0 <= pslot < 4
            assert FaultPlan.from_json(p.to_json()) == p

    def test_sample_serving_validation(self):
        with pytest.raises(ValueError, match="total_steps"):
            FaultPlan.sample_serving(0, 3, max_slots=2)
        with pytest.raises(ValueError, match="max_slots"):
            FaultPlan.sample_serving(0, 8, max_slots=0)

    def test_slow_decode_stretches_steps(self, engine):
        sched, _ = _sched(engine,
                          fault_plan=FaultPlan(slow_decode_s=0.02))
        t0 = time.perf_counter()
        sched.run([Request(prompt=[1], max_new_tokens=4)])
        assert time.perf_counter() - t0 >= 3 * 0.02  # 3 decode steps


class TestChaosRun:
    """The deterministic chaos leg: flood + poison + slow step in ONE
    FaultPlan.sample_serving-driven run — bounded queue, only the
    poisoned slot retired, every other greedy stream identical to the
    fault-free run, flat compile counters under recompile_guard."""

    SEED = 23  # sample_serving(23, 10, max_slots=2):
    #            flood at an early step, poison in [5, 10)

    def _drive(self, qengine, plan, max_queue):
        reg = MetricsRegistry()
        sched = SlotScheduler(qengine, registry=reg, max_queue=max_queue,
                              fault_plan=plan, dump_dir="/tmp")
        rng = np.random.RandomState(0)

        def fresh(i):
            return Request(prompt=[1 + int(rng.randint(90)), 2],
                           max_new_tokens=10, request_id=100 + i)

        for i in range(4):
            sched.submit(fresh(i))
        submitted, rejections, max_depth = 4, [], 0
        while sched.pending:
            if plan is not None:
                for _ in range(plan.flood_n(sched.steps + 1)):
                    r = sched.submit(fresh(submitted))
                    submitted += 1
                    if isinstance(r, Rejection):
                        rejections.append(r)
            sched.step()
            max_depth = max(max_depth, len(sched.queue))
        return sched, reg, rejections, max_depth

    def test_flood_poison_slow_in_one_run(self, qengine):
        plan = FaultPlan.sample_serving(self.SEED, 10, max_slots=2,
                                        flood_n=6, slow_decode_s=0.002)
        # the identical request schedule, faults stripped: the flood
        # still happens (same driver), poison/slow removed
        clean_plan = FaultPlan(flood=dict(plan.flood))

        clean, *_ = self._drive(qengine, clean_plan, max_queue=4)
        sched, reg, rejections, max_depth = self._drive(
            qengine, plan, max_queue=4)

        # bounded queue + typed rejections under the flood
        assert max_depth <= 4
        assert rejections and all(r.reason == "queue_full"
                                  for r in rejections)
        # exactly one poisoned retirement...
        snap = reg.snapshot()
        assert snap["serve/poisoned"] == 1.0
        poisoned = [c for c in sched.completed
                    if c.finish_reason == "poisoned"]
        assert len(poisoned) == 1
        # ...and every other completed request's greedy stream is
        # byte-identical to the fault-free run's
        clean_out = {c.request_id: c for c in clean.completed}
        for c in sched.completed:
            if c.finish_reason == "poisoned" or c.request_id \
                    not in clean_out:
                continue
            if clean_out[c.request_id].finish_reason == "length":
                assert c.tokens == clean_out[c.request_id].tokens, \
                    c.request_id

    def test_chaos_run_zero_recompiles(self, qengine):
        from apex_tpu.analysis.program import recompile_guard

        plan = FaultPlan.sample_serving(self.SEED, 10, max_slots=2,
                                        flood_n=4)
        reg = MetricsRegistry()
        sched = SlotScheduler(qengine, registry=reg, max_queue=4,
                              fault_plan=plan, dump_dir="/tmp")
        for i in range(4):
            sched.submit(Request(prompt=[3 + i, 4], max_new_tokens=10))
        with recompile_guard("chaos") as guard:
            first = True
            while sched.pending:
                for _ in range(plan.flood_n(sched.steps + 1)):
                    sched.submit(Request(prompt=[7, 8],
                                         max_new_tokens=10))
                sched.step()
                if first:
                    guard.rebase()
                    first = False
        assert reg.snapshot()["serve/poisoned"] >= 1.0
