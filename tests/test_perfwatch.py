"""The performance observatory: the append-only ``BenchHistory`` store
(schema, persistence, the ``BENCH_r*.json`` importer), the
rolling-median+MAD ``RegressionDetector`` (flat-noise silence, planted
step fires exactly once, unit-inferred direction pinned against
bench.py's actual emitted units), ``AttributionDiff`` suspect naming,
cost-model drift series/shift alerts + the ``perf/model_drift``
gauges, the CLI exit-code contract, and the round-trip precision
guarantee: a 0.3% delta that the printed 2-decimal display value
quantizes away survives in ``raw_value`` through ``bench.py::_emit``.

The perfwatch module is jax-free on purpose; only the bench round-trip
test touches the jax-importing ``bench`` module.
"""

import ast
import json
import os
import subprocess
import sys

import pytest

from apex_tpu.observability import perfwatch as pw
from apex_tpu.observability.registry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# records + schema
# ---------------------------------------------------------------------------

class TestRecordSchema:
    def test_make_record_rounds_display_keeps_raw(self):
        rec = pw.make_record("m", 2047.5139, "imgs/sec", 0.8289,
                             git_sha="s", host="h")
        assert rec["value"] == 2047.51
        assert rec["raw_value"] == 2047.5139
        assert rec["unit"] == "imgs/sec" and rec["vs_baseline"] == 0.8289

    def test_extras_promote_through_the_field_table(self):
        rec = pw.make_record(
            "m", 1.0, "ms", git_sha="s", host="h",
            extras={"config": {"zero": 1}, "modeled_step_ms": 5.0,
                    "mfu": 0.41})
        # table-listed extras become top-level keys; the rest rides
        # under extra — so validate_record stays total over the table
        assert rec["config"] == {"zero": 1}
        assert rec["modeled_step_ms"] == 5.0
        assert rec["extra"] == {"mfu": 0.41}
        pw.validate_record(rec)

    def test_validate_rejects_rogue_and_missing(self):
        rec = pw.make_record("m", 1.0, "ms", git_sha="s", host="h")
        with pytest.raises(ValueError, match="missing"):
            pw.validate_record({k: v for k, v in rec.items()
                                if k != "raw_value"})
        with pytest.raises(ValueError, match="rogue"):
            pw.validate_record(dict(rec, rogue=1))

    def test_provenance_defaults_are_stamped(self):
        rec = pw.make_record("m", 1.0, "ms")
        assert rec["git_sha"] and rec["host"]
        assert "/py%d.%d" % sys.version_info[:2] in rec["host"]


class TestBenchHistory:
    def _rec(self, metric="m", value=1.0, unit="ms", **kw):
        kw.setdefault("git_sha", "s")
        kw.setdefault("host", "h")
        return pw.make_record(metric, value, unit, **kw)

    def test_append_persists_and_reloads(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        hist = pw.BenchHistory(path)
        hist.append(self._rec("a", 1.0))
        hist.append(self._rec("b", 2.0))
        hist.append(self._rec("a", 3.0))
        back = pw.BenchHistory(path)
        assert len(back) == 3
        assert back.metrics() == ["a", "b"]
        assert [r["raw_value"] for r in back.series("a")] == [1.0, 3.0]

    def test_append_validates_before_writing(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        hist = pw.BenchHistory(path)
        with pytest.raises(ValueError):
            hist.append({"metric": "m"})
        assert not os.path.exists(path)  # nothing half-written

    def test_corrupt_line_fails_loudly_on_load(self, tmp_path):
        path = tmp_path / "h.jsonl"
        path.write_text("not json\n")
        with pytest.raises(ValueError, match="h.jsonl:1"):
            pw.BenchHistory(str(path))

    def test_importer_ingests_and_is_idempotent(self, tmp_path):
        dump = {"n": 7, "cmd": "bench", "rc": 0, "tail": "\n".join([
            "some log line",
            json.dumps({"metric": "tps", "value": 100.25,
                        "unit": "tokens/sec", "vs_baseline": 0.9,
                        "mfu": 0.4}),
            json.dumps({"metric": "lat", "value": 3.14, "unit": "ms",
                        "vs_baseline": None}),
        ])}
        path = tmp_path / "BENCH_r07.json"
        path.write_text(json.dumps(dump))
        hist = pw.BenchHistory()
        assert hist.import_bench_files([str(path)]) == 2
        assert hist.import_bench_files([str(path)]) == 0  # idempotent
        (tps,) = hist.series("tps")
        assert tps["run"] == "r07" and tps["source"] == "BENCH_r07.json"
        assert tps["raw_value"] == 100.25 and tps["git_sha"] == "import"
        assert tps["extra"] == {"mfu": 0.4}

    def test_importer_reads_this_repos_real_dumps(self):
        hist = pw.BenchHistory()
        added = hist.import_bench_files(root=REPO)
        # BENCH_r01..r05 are checked in: 4 resnet rounds + round 5's
        # full sweep — and every imported record passes the schema
        assert added >= 10
        assert "resnet50_train_imgs_per_sec_per_chip" in hist.metrics()
        for rec in hist:
            pw.validate_record(rec)


# ---------------------------------------------------------------------------
# the detector
# ---------------------------------------------------------------------------

class TestRegressionDetector:
    def test_flat_series_with_noise_stays_silent(self):
        det = pw.RegressionDetector()
        # +-0.5% deterministic jitter: inside the 2% noise floor
        noise = (0.004, -0.003, 0.005, -0.005, 0.002, -0.004)
        values = [100.0 * (1.0 + noise[i % len(noise)])
                  for i in range(24)]
        assert det.check_series(values, direction=1) == []
        assert det.check_series(values, direction=-1) == []

    def test_planted_step_fires_exactly_once(self):
        det = pw.RegressionDetector()
        values = [100.0] * 10 + [80.0] * 6  # 20% drop, level persists
        firings = det.check_series(values, direction=1)
        assert len(firings) == 1
        i, baseline, delta, thresh = firings[0]
        assert i == 10 and baseline == 100.0
        assert abs(delta + 0.20) < 1e-9 and delta < -thresh

    def test_direction_gates_what_counts_as_bad(self):
        det = pw.RegressionDetector()
        up = [100.0] * 6 + [120.0] * 3
        # a 20% jump is an improvement up-is-good, a regression
        # down-is-good — same series, opposite verdicts
        assert det.check_series(up, direction=1) == []
        assert len(det.check_series(up, direction=-1)) == 1
        assert len(det.check_series(up, two_sided=True)) == 1

    def test_learned_floor_beats_the_static_one_on_noisy_series(self):
        det = pw.RegressionDetector()
        # ~6% swings are this series' OWN noise: the MAD-learned
        # threshold must absorb a swing the 2% static floor would flag
        values = [100.0, 106.0, 94.0, 105.0, 95.0, 106.0, 94.0,
                  105.0, 95.0, 106.0]
        assert det.check_series(values, direction=1) == []

    def test_check_attaches_suspect_region(self):
        clean, planted = pw.selfcheck()
        assert clean == []
        assert planted, "planted 20% drop must fire"
        assert all(r.suspect_region == "gpt_attention" for r in planted)
        assert all(r.suspect_delta_ms > 0 for r in planted)
        msg = planted[0].message()
        assert "gpt_fast_tokens_per_sec" in msg
        assert "-20" in msg and "gpt_attention" in msg

    def test_unit_direction_table_pinned(self):
        assert pw.unit_direction("imgs/sec") == 1
        assert pw.unit_direction("tokens/sec") == 1
        assert pw.unit_direction("percent") == 1
        assert pw.unit_direction("ms") == -1
        assert pw.unit_direction("bytes") == -1
        assert pw.unit_direction("skipped") == 0
        assert pw.unit_direction("error") == 0
        # suffix inference covers spellings the table never listed
        assert pw.unit_direction("reqs/sec") == 1
        assert pw.unit_direction("step_ms") == -1
        assert pw.unit_direction("furlongs") == 0

    def test_every_bench_emitted_unit_has_a_direction(self):
        """The direction table is pinned against bench.py's ACTUAL
        emitted units: every literal unit passed to ``_emit`` must be
        direction-carrying (or one of the two non-series markers), so a
        new bench line can never silently fall out of the detector."""
        with open(os.path.join(REPO, "bench.py")) as f:
            tree = ast.parse(f.read())
        units = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "_emit"
                    and len(node.args) >= 3
                    and isinstance(node.args[2], ast.Constant)):
                units.add(node.args[2].value)
        assert {"imgs/sec", "tokens/sec", "ms"} <= units  # scan works
        for unit in units:
            if unit in ("skipped", "error"):
                continue
            assert pw.unit_direction(unit) != 0, unit


# ---------------------------------------------------------------------------
# attribution diffs
# ---------------------------------------------------------------------------

class TestAttributionDiff:
    def test_suspect_is_the_region_that_grew_most(self):
        before = [{"region": "embed", "modeled_ms": 0.5},
                  {"region": "attn", "modeled_ms": 3.0},
                  {"region": "mlp", "modeled_ms": 2.0}]
        after = [{"region": "embed", "modeled_ms": 0.5},
                 {"region": "attn", "modeled_ms": 4.2},
                 {"region": "mlp", "modeled_ms": 1.9}]
        diff = pw.AttributionDiff(before, after)
        worst = diff.suspect()
        assert worst.region == "attn" and worst.basis == "modeled"
        assert abs(worst.delta_ms - 1.2) < 1e-9
        assert "attn" in diff.markdown()

    def test_measured_preferred_over_modeled(self):
        before = [{"region": "attn", "modeled_ms": 3.0,
                   "measured_ms": 3.5}]
        after = [{"region": "attn", "modeled_ms": 3.0,
                  "measured_ms": 4.5}]
        (delta,) = pw.AttributionDiff(before, after).regions
        assert delta.basis == "measured" and delta.delta_ms == 1.0

    def test_nothing_grew_means_no_suspect(self):
        rep = [{"region": "attn", "modeled_ms": 3.0}]
        assert pw.AttributionDiff(rep, rep).suspect() is None


# ---------------------------------------------------------------------------
# cost-model drift
# ---------------------------------------------------------------------------

def _drift_history(ratios, metric="step_ms"):
    hist = pw.BenchHistory()
    for i, ratio in enumerate(ratios):
        hist.record(metric, 5.0 * ratio, "ms", run=f"r{i:02d}",
                    git_sha="s", host="h",
                    extras={"modeled_step_ms": 5.0,
                            "step_time_ms": 5.0 * ratio})
    return hist


class TestModelDrift:
    def test_series_is_measured_over_modeled(self):
        hist = _drift_history([1.30, 1.31, 1.29])
        (pts,) = pw.drift_series(hist).values()
        assert [round(r, 2) for _, _, r in pts] == [1.30, 1.31, 1.29]

    def test_stable_gap_is_not_an_alert(self):
        # a constant 30% model gap is a LEVEL, not a shift
        hist = _drift_history([1.30] * 8)
        assert pw.detect_drift_shifts(hist) == []

    def test_shift_alerts_both_directions(self):
        worse = _drift_history([1.30] * 6 + [1.60] * 2)
        (shift,) = pw.detect_drift_shifts(worse)
        assert shift.ratio == 1.60 and shift.delta_frac > 0
        assert "model-drift" in shift.message()
        better = _drift_history([1.30] * 6 + [1.05] * 2)
        (shift,) = pw.detect_drift_shifts(better)
        assert shift.delta_frac < 0  # improvements alert too

    def test_publish_drift_gauges(self):
        hist = _drift_history([1.30, 1.40], metric="a")
        for i, ratio in enumerate([0.50, 0.60]):
            hist.record("b", 5.0 * ratio, "ms", run=f"r{i:02d}",
                        git_sha="s", host="h",
                        extras={"modeled_step_ms": 5.0,
                                "step_time_ms": 5.0 * ratio})
        reg = MetricsRegistry()
        latest = pw.publish_drift(hist, reg)
        assert latest == {"a": 1.40, "b": 0.60}
        snap = reg.snapshot()
        assert snap["perf/model_drift/a"] == 1.40
        assert snap["perf/model_drift/b"] == 0.60
        # the scalar is the worst |log ratio|: 0.60 beats 1.40
        assert snap["perf/model_drift"] == 0.60


# ---------------------------------------------------------------------------
# the CLI contract (jax-free, so subprocess is cheap)
# ---------------------------------------------------------------------------

def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "apex_tpu.perfwatch"] + list(argv),
        capture_output=True, text=True, cwd=REPO)


class TestCLI:
    def _write_history(self, tmp_path, planted):
        path = str(tmp_path / "h.jsonl")
        disk = pw.BenchHistory(path)
        for rec in pw.synthetic_history(planted=planted):
            disk.append(rec)
        return path

    def test_check_clean_exits_zero(self, tmp_path):
        path = self._write_history(tmp_path, planted=False)
        proc = _run_cli("--check", "--history", path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "-> clean" in proc.stdout

    def test_check_planted_exits_one_naming_the_region(self, tmp_path):
        path = self._write_history(tmp_path, planted=True)
        proc = _run_cli("--check", "--history", path)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "gpt_fast_tokens_per_sec" in proc.stdout
        assert "-20" in proc.stdout          # the delta
        assert "gpt_attention" in proc.stdout  # the suspect region

    def test_selfcheck_exit_codes(self):
        proc = _run_cli("--selfcheck")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "selfcheck ok" in proc.stdout

    def test_report_renders_markdown(self, tmp_path):
        path = self._write_history(tmp_path, planted=True)
        proc = _run_cli("--report", "-", "--history", path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "# Performance observatory" in proc.stdout
        assert "gpt_attention" in proc.stdout

    def test_bootstrap_ingests_the_checked_in_rounds(self):
        # no --history: the CLI bootstraps in-memory from the repo's
        # own BENCH_r*.json dumps — the acceptance path
        proc = _run_cli("--check", "--root", REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# the bench.py round trip: satellite 1's precision guarantee
# ---------------------------------------------------------------------------

class TestBenchRoundTrip:
    def test_sub_display_precision_delta_survives(self, tmp_path,
                                                  monkeypatch, capsys):
        """0.1000 vs 0.1003 both PRINT as 0.1 — the 2-decimal display
        quantization that forced gpt_decode_goodput into percent. The
        history's raw_value must keep the 0.3% delta alive for the
        detector."""
        import bench
        path = str(tmp_path / "h.jsonl")
        monkeypatch.setenv("APEX_BENCH_HISTORY", path)
        monkeypatch.setattr(bench, "_HISTORY", None)
        monkeypatch.setattr(bench, "_RESULTS", [])
        bench._emit("rt_ms", 0.1000, "ms", None)
        bench._emit("rt_ms", 0.1003, "ms", None)
        printed = [json.loads(line)
                   for line in capsys.readouterr().out.splitlines()]
        assert [p["value"] for p in printed] == [0.1, 0.1]  # quantized
        back = pw.BenchHistory(path)
        raw = [r["raw_value"] for r in back.series("rt_ms")]
        assert raw == [0.1000, 0.1003]
        assert abs(raw[1] / raw[0] - 1.003) < 1e-9

    def test_emit_keeps_attribution_out_of_printed_lines(
            self, tmp_path, monkeypatch, capsys):
        import bench
        path = str(tmp_path / "h.jsonl")
        monkeypatch.setenv("APEX_BENCH_HISTORY", path)
        monkeypatch.setattr(bench, "_HISTORY", None)
        monkeypatch.setattr(bench, "_RESULTS", [])
        bench._emit("rt2_ms", 5.2, "ms", None,
                    modeled_step_ms=5.0, step_time_ms=5.2,
                    attribution=[{"region": "attn", "modeled_ms": 3.0}])
        (line,) = [json.loads(x)
                   for x in capsys.readouterr().out.splitlines()]
        # printed line keeps its pre-observatory shape
        assert "attribution" not in line and "step_time_ms" not in line
        assert line["modeled_step_ms"] == 5.0
        (rec,) = pw.BenchHistory(path).series("rt2_ms")
        # ... while the history record carries the full breakdown
        assert rec["attribution"] == [{"region": "attn",
                                       "modeled_ms": 3.0}]
        assert rec["step_time_ms"] == 5.2
        # and the drift series sees the pair immediately
        (pts,) = pw.drift_series(pw.BenchHistory(path)).values()
        assert abs(pts[0][2] - 5.2 / 5.0) < 1e-9

    def test_disabled_history_is_a_no_op(self, monkeypatch, capsys):
        import bench
        monkeypatch.setenv("APEX_BENCH_HISTORY", "off")
        monkeypatch.setattr(bench, "_HISTORY", None)
        monkeypatch.setattr(bench, "_RESULTS", [])
        bench._emit("rt3_ms", 1.0, "ms", None)
        assert bench._history() is None
        assert json.loads(capsys.readouterr().out)["value"] == 1.0
