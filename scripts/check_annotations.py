#!/usr/bin/env python
"""Static check: the documented hot-path ``jax.named_scope`` annotations
still exist in source.

The annotate -> trace -> attribute workflow (``utils/timers.py`` module
docstring, ``docs/OBSERVABILITY.md``) depends on four names showing up in
HLO op metadata so captured profiles stay attributable; a refactor that
drops one silently rots the trace-viewer contract. This script greps the
exact ``named_scope("<name>")`` strings out of the owning sources — no jax
import, so it runs anywhere, pre-commit fast — and exits non-zero listing
anything missing. Wired into the test suite via
``tests/test_observability.py::test_check_annotations_script``.

Usage::

    python scripts/check_annotations.py          # check, report, exit 0/1
    python scripts/check_annotations.py --list   # print the contract
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# annotation -> source files allowed to carry it (repo-relative). The
# contract is "exists in at least one of its owning files": moving an
# annotation to an unrelated module is a docs-breaking change and should
# fail here until the table (and docs) are updated. The table doubles as
# the pyprof attribution-region vocabulary: apex_tpu/pyprof/model.py's
# DEFAULT_REGIONS must stay a subset of these keys (asserted in
# tests/test_pyprof.py), so every region a step-time attribution report
# names is guaranteed to exist as a named_scope in source.
ANNOTATIONS = {
    "apex_ddp_allreduce": ["apex_tpu/parallel/distributed.py"],
    "apex_ddp_bucketed_allreduce": ["apex_tpu/parallel/distributed.py"],
    "sync_bn_stats": ["apex_tpu/parallel/sync_batchnorm.py"],
    "pipeline_tick": [
        "apex_tpu/transformer/pipeline_parallel/schedules.py"],
    "flash_attention": ["apex_tpu/ops/flash_attention.py"],
    "optimizer_step": ["apex_tpu/optimizers/_base.py"],
    # model phases (pyprof attribution regions)
    "gpt_embed": ["apex_tpu/models/gpt.py"],
    "gpt_ln": ["apex_tpu/models/gpt.py"],
    "gpt_attention": ["apex_tpu/models/gpt.py"],
    "gpt_mlp": ["apex_tpu/models/gpt.py"],
    "gpt_head_loss": ["apex_tpu/models/gpt.py"],
    "rn50_stem": ["apex_tpu/models/resnet.py"],
    "rn50_body": ["apex_tpu/models/resnet.py"],
    "rn50_head": ["apex_tpu/models/resnet.py"],
    # tensor-parallel layers (GEMM + dependent collective, tp > 1 only)
    "tp_column_linear": [
        "apex_tpu/transformer/tensor_parallel/layers.py"],
    "tp_row_linear": [
        "apex_tpu/transformer/tensor_parallel/layers.py"],
    # serving fast path: the decode kernel plus the two AOT step bodies,
    # so pyprof attributes prefill vs decode (docs/SERVING.md)
    "decode_attention": ["apex_tpu/ops/flash_attention.py"],
    "serve_prefill": ["apex_tpu/serving/engine.py"],
    "serve_decode": ["apex_tpu/serving/engine.py"],
}


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    lines = []
    ok = True
    for name, files in sorted(ANNOTATIONS.items()):
        needle = f'named_scope("{name}")'
        found_in = []
        for rel in files:
            path = os.path.join(repo, rel)
            try:
                with open(path) as f:
                    if needle in f.read():
                        found_in.append(rel)
            except OSError:
                pass
        if found_in:
            lines.append(f"ok       {name}: {', '.join(found_in)}")
        else:
            ok = False
            lines.append(f"MISSING  {name}: expected "
                         f'{needle} in {" or ".join(files)}')
    return ok, lines


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        for name, files in sorted(ANNOTATIONS.items()):
            print(f"{name}\t{','.join(files)}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("hot-path trace annotations missing — update the source or "
              "the contract table in scripts/check_annotations.py + "
              "docs/OBSERVABILITY.md", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
