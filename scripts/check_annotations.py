#!/usr/bin/env python
"""Shim: the named_scope annotation contract moved into the unified
static-analysis engine (``apex_tpu.analysis``, rule ``ast-annotations``;
table: ``ANNOTATIONS`` in ``apex_tpu/analysis/rules_ast.py``, docs:
``docs/ANALYSIS.md``). This script keeps the historical CLI +
``check(repo) -> (ok, lines)`` surface::

    python scripts/check_annotations.py          # check, report, exit 0/1
    python scripts/check_annotations.py --list   # print the contract
    python -m apex_tpu.analysis --rule ast-annotations   # same rule
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.analysis.astlint import repo_root
from apex_tpu.analysis.core import findings_to_ok_lines
from apex_tpu.analysis.rules_ast import ANNOTATIONS, rule_annotations

REPO = repo_root()


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    return findings_to_ok_lines(*rule_annotations(repo))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        for name, files in sorted(ANNOTATIONS.items()):
            print(f"{name}\t{','.join(files)}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("hot-path trace annotations missing — update the source or "
              "the contract table (ANNOTATIONS in "
              "apex_tpu/analysis/rules_ast.py) + docs/OBSERVABILITY.md",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
