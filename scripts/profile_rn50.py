"""Ablation attribution for the headline RN50 train step (docs/PERF.md).

Times the full bench-identical step, then a ladder of ablations that each
remove one cost component; the deltas attribute the step time. Every
ablation threads a scalar that depends on ALL the compute it claims to
measure, so XLA cannot dead-code-eliminate the work.

Run on the bench chip:  python scripts/profile_rn50.py
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp.scaler import DynamicLossScale, all_finite
from apex_tpu.models import ResNet50, ResNetConfig
from apex_tpu.optimizers import FlatOptimizer, FusedSGD
from apex_tpu.utils.timers import device_fence


def timeit(fn, args, iters=30, warmup=5, chunk=10):
    out = args
    for _ in range(warmup):
        out = fn(*out)
    device_fence(out)
    t0 = time.perf_counter()
    device_fence(out)
    rtt = time.perf_counter() - t0
    per = []
    for _ in range(max(1, iters // chunk)):
        t0 = time.perf_counter()
        for _ in range(chunk):
            out = fn(*out)
        device_fence(out)
        per.append(max(time.perf_counter() - t0 - rtt, 1e-9) / chunk)
    return float(np.mean(per) * 1e3), float(np.std(per) * 1e3)


def tree_sum(t):
    return sum(jnp.sum(l.astype(jnp.float32))
               for l in jax.tree_util.tree_leaves(t))


def main(bn_compute_apply=True):
    batch, img = 256, 224
    cfg = ResNetConfig(num_classes=1000, compute_dtype=jnp.bfloat16,
                       bn_apply_compute_dtype=bn_compute_apply)
    model = ResNet50(cfg)
    params, bn_state = model.init(jax.random.PRNGKey(0))
    opt = FlatOptimizer(FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4))
    opt_state = opt.init(params)
    scaler = DynamicLossScale(init_scale=2.0 ** 12)
    ls = scaler.init()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, img, img, 3), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, batch))
    copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)

    def loss_fn(params, bn_state, scale, training=True):
        logits, new_bn = model(params, bn_state, x, training=training)
        onehot = jax.nn.one_hot(labels, 1000)
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
        return loss * scale, (loss, new_bn)

    results = {}

    # 1. full bench-identical step
    @(lambda f: jax.jit(f, donate_argnums=(0, 1, 2, 3)))
    def full_step(params, bn_state, opt_state, ls):
        grads, (loss, new_bn) = jax.grad(loss_fn, has_aux=True)(
            params, bn_state, ls.loss_scale)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite,
                                     scale=1.0 / ls.loss_scale)
        return params, new_bn, opt_state, new_ls

    c = full_step.lower(params, bn_state, opt_state, ls).compile()
    results["full_step"] = timeit(
        c, (copy(params), copy(bn_state), copy(opt_state), copy(ls)))

    # 2. fwd+bwd only: all grads kept live via a full-tree reduction
    @jax.jit
    def fwd_bwd(params, bn_state, acc):
        grads, (loss, new_bn) = jax.grad(loss_fn, has_aux=True)(
            params, bn_state, 1.0)
        return params, new_bn, acc * 0.0 + tree_sum(grads) + loss

    results["fwd_bwd_only"] = timeit(
        fwd_bwd, (params, bn_state, jnp.float32(0)))

    # 3. fwd only, training-mode BN (batch stats computed)
    @jax.jit
    def fwd_train(params, bn_state, acc):
        _, (loss, new_bn) = loss_fn(params, bn_state, 1.0)
        return params, new_bn, acc * 0.0 + loss

    results["fwd_train"] = timeit(
        fwd_train, (params, bn_state, jnp.float32(0)))

    # 4. fwd only, eval-mode BN (running stats; no batch reductions)
    @jax.jit
    def fwd_eval(params, bn_state, acc):
        _, (loss, _) = loss_fn(params, bn_state, 1.0, training=False)
        return params, bn_state, acc * 0.0 + loss

    results["fwd_eval"] = timeit(
        fwd_eval, (params, bn_state, jnp.float32(0)))

    # 5. fwd+bwd with eval-mode BN — batch-stat cost inside the whole
    #    differentiated program
    @jax.jit
    def fwd_bwd_eval(params, bn_state, acc):
        def lf(p):
            s, _ = loss_fn(p, bn_state, 1.0, training=False)
            return s
        grads = jax.grad(lf)(params)
        return params, bn_state, acc * 0.0 + tree_sum(grads)

    results["fwd_bwd_evalbn"] = timeit(
        fwd_bwd_eval, (params, bn_state, jnp.float32(0)))

    # 6. optimizer+scaler alone on realistic grads
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(jnp.shape(p), 1e-4, jnp.float32), params)

    @(lambda f: jax.jit(f, donate_argnums=(0, 1, 2)))
    def opt_only(params, opt_state, ls):
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite,
                                     scale=1.0 / ls.loss_scale)
        return params, opt_state, new_ls

    results["opt_scaler_only"] = timeit(
        opt_only, (copy(params), copy(opt_state), copy(ls)))

    for k, (ms, std) in results.items():
        print(json.dumps({"phase": k, "bn_compute_apply": bn_compute_apply,
                          "ms": round(ms, 3), "std": round(std, 3)}),
              flush=True)

    try:
        ca = c.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        out = {k: float(v) for k, v in ca.items()
               if k in ("flops", "bytes accessed", "optimal_seconds")}
        print(json.dumps({"cost_analysis": out}))
    except Exception as e:
        print("cost_analysis failed:", e)


if __name__ == "__main__":
    import sys
    if "--ab" in sys.argv:
        main(bn_compute_apply=False)
        main(bn_compute_apply=True)
    else:
        main()
