#!/usr/bin/env python
"""Shim: the collective-routing contract moved into the unified
static-analysis engine (``apex_tpu.analysis``, rule ``ast-collectives``;
allowlists: ``ALLOWED_GATHER``/``ALLOWED_SCATTER``/``GRAD_SYNC_PREFIXES``
in ``apex_tpu/analysis/rules_ast.py``, docs: ``docs/ANALYSIS.md``). The
program-level twin — which also catches a helper that reaches
``lax.psum`` through indirection — is the ``jaxpr-collectives`` rule.
Historical CLI preserved::

    python scripts/check_collectives.py          # check, report, exit 0/1
    python scripts/check_collectives.py --list   # print the policy
    python -m apex_tpu.analysis --rule ast-collectives   # same rule
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.analysis.astlint import repo_root
from apex_tpu.analysis.core import findings_to_ok_lines
from apex_tpu.analysis.rules_ast import (ALLOWED_GATHER, ALLOWED_SCATTER,
                                         GRAD_SYNC_PREFIXES,
                                         rule_collectives)

REPO = repo_root()


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    return findings_to_ok_lines(*rule_collectives(repo))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        print("allowed lax.all_gather call sites:")
        for rel in sorted(ALLOWED_GATHER):
            print(f"  {rel}")
        print("allowed lax.psum_scatter call sites:")
        for rel in sorted(ALLOWED_SCATTER):
            print(f"  {rel}")
        print("grad-sync modules (no raw lax.psum/lax.psum_scatter):")
        for rel in GRAD_SYNC_PREFIXES:
            print(f"  {rel}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("raw collective call sites found — route gathers through "
              "apex_tpu/utils/vma.py and grad syncs through "
              "apex_tpu/parallel/distributed.py (or extend the allowlists "
              "in apex_tpu/analysis/rules_ast.py with justification)",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
