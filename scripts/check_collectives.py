#!/usr/bin/env python
"""Static check: no raw ``lax.all_gather`` outside the VMA-safe wrappers.

Gathers are the one collective whose semantics changed across the jax
version line this library straddles: on VMA jax ``all_gather`` demands a
device-varying operand (a replicated-typed value must be ``pcast`` first)
and there is a separate invariant-typed gather, while on the pre-VMA 0.4.x
line neither concept exists. ``apex_tpu.utils.vma`` owns both shims
(:func:`varying_all_gather`, :func:`invariant_all_gather`); a raw
``jax.lax.all_gather`` sprinkled anywhere else silently works on one
version and breaks on the other. This script greps the package for stray
call sites — no jax import, pre-commit fast — and exits non-zero listing
any. Wired into the test suite via
``tests/test_observability.py::TestCheckCollectives``.

Usage::

    python scripts/check_collectives.py          # check, report, exit 0/1
    python scripts/check_collectives.py --list   # print the policy
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "apex_tpu"

# the only modules allowed to touch lax.all_gather directly: the VMA shims
# themselves and the version-compat layer
ALLOWED = {
    os.path.join("apex_tpu", "utils", "vma.py"),
    os.path.join("apex_tpu", "utils", "compat.py"),
}

# `lax.all_gather(` catches `jax.lax.all_gather(` and `from jax import lax;
# lax.all_gather(`; the word boundary keeps `all_gather_invariant` (the
# private symbol vma.py wraps) and mention-in-docstring text like
# "all_gather the shards" out
_PATTERN = re.compile(r"lax\.all_gather\s*\(")


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    lines = []
    ok = True
    pkg_root = os.path.join(repo, PACKAGE)
    for dirpath, _dirnames, filenames in sorted(os.walk(pkg_root)):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo)
            with open(path) as f:
                source = f.read()
            hits = [i + 1 for i, line in enumerate(source.splitlines())
                    if _PATTERN.search(line)]
            if not hits:
                continue
            if rel in ALLOWED:
                lines.append(f"ok       {rel}: wrapper module "
                             f"(lines {', '.join(map(str, hits))})")
            else:
                ok = False
                for ln in hits:
                    lines.append(
                        f"RAW      {rel}:{ln}: lax.all_gather outside the "
                        f"VMA-safe wrappers — use "
                        f"apex_tpu.utils.vma.varying_all_gather (or "
                        f"invariant_all_gather)")
    return ok, lines


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        print("allowed lax.all_gather call sites:")
        for rel in sorted(ALLOWED):
            print(f"  {rel}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("raw all_gather call sites found — route them through "
              "apex_tpu/utils/vma.py so the pre-VMA 0.4.x path keeps "
              "working (or extend ALLOWED in scripts/check_collectives.py "
              "with justification)", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
