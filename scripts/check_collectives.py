#!/usr/bin/env python
"""Static check: collectives stay behind their chokepoints.

Two routing contracts, one fast grep (no jax import, pre-commit fast),
wired into the test suite via
``tests/test_observability.py::TestCheckCollectives``:

1. **Gathers** — the one collective whose semantics changed across the jax
   version line this library straddles: on VMA jax ``all_gather`` demands a
   device-varying operand (a replicated-typed value must be ``pcast``
   first) and there is a separate invariant-typed gather, while on the
   pre-VMA 0.4.x line neither concept exists. ``apex_tpu.utils.vma`` owns
   both shims (:func:`varying_all_gather`, :func:`invariant_all_gather`);
   a raw ``jax.lax.all_gather`` sprinkled anywhere else silently works on
   one version and breaks on the other.

2. **Gradient syncs** — ``apex_tpu.parallel.distributed`` is the bucketing
   engine: every DP grad reduction must flow through
   :func:`allreduce_grads` / :func:`grouped_psum` /
   :func:`reduce_scatter_grads` so ``bucket_bytes`` policy, telemetry
   (``ddp/*``), and the health watchdog see it. Raw ``lax.psum_scatter``
   is flagged package-wide outside the chokepoint module (the only other
   legitimate holder is the context-parallel *activation* scatter, which
   is not a grad sync and is allowlisted); raw ``lax.psum`` /
   ``lax.psum_scatter`` are flagged inside the grad-handling modules
   (``training.py``, ``optimizers/``), where any psum IS a grad-path
   reduction or belongs in the chokepoint anyway.

Usage::

    python scripts/check_collectives.py          # check, report, exit 0/1
    python scripts/check_collectives.py --list   # print the policy
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "apex_tpu"


def _p(*parts: str) -> str:
    return os.path.join(*parts)


# the only modules allowed to touch lax.all_gather directly: the VMA shims
# themselves and the version-compat layer
ALLOWED_GATHER = {
    _p("apex_tpu", "utils", "vma.py"),
    _p("apex_tpu", "utils", "compat.py"),
}

# lax.psum_scatter: the grad-sync chokepoint (reduce_scatter_grads), plus
# the context-parallel sequence-dim scatter — an ACTIVATION collective
# (RowParallel output path along the sequence axis), not a gradient sync,
# so it does not belong behind the bucketing engine
ALLOWED_SCATTER = {
    _p("apex_tpu", "parallel", "distributed.py"),
    _p("apex_tpu", "transformer", "context_parallel.py"),
}

# modules whose psums are gradient-path reductions by construction: any
# raw lax.psum / lax.psum_scatter here must route through the
# parallel/distributed.py chokepoints (allreduce_grads / grouped_psum /
# reduce_scatter_grads) so bucketing policy cannot be bypassed
GRAD_SYNC_PREFIXES = (
    _p("apex_tpu", "training.py"),
    _p("apex_tpu", "optimizers") + os.sep,
)

_GATHER = re.compile(r"lax\.all_gather\s*\(")
_SCATTER = re.compile(r"lax\.psum_scatter\s*\(")
_PSUM = re.compile(r"lax\.psum\s*\(")


def _hits(pattern: re.Pattern, source: str):
    return [i + 1 for i, line in enumerate(source.splitlines())
            if pattern.search(line)]


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    lines = []
    ok = True
    pkg_root = os.path.join(repo, PACKAGE)
    for dirpath, _dirnames, filenames in sorted(os.walk(pkg_root)):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo)
            with open(path) as f:
                source = f.read()

            hits = _hits(_GATHER, source)
            if hits:
                if rel in ALLOWED_GATHER:
                    lines.append(f"ok       {rel}: gather wrapper module "
                                 f"(lines {', '.join(map(str, hits))})")
                else:
                    ok = False
                    for ln in hits:
                        lines.append(
                            f"RAW      {rel}:{ln}: lax.all_gather outside "
                            f"the VMA-safe wrappers — use "
                            f"apex_tpu.utils.vma.varying_all_gather (or "
                            f"invariant_all_gather)")

            hits = _hits(_SCATTER, source)
            if hits:
                if rel in ALLOWED_SCATTER:
                    lines.append(f"ok       {rel}: psum_scatter chokepoint/"
                                 f"allowlisted "
                                 f"(lines {', '.join(map(str, hits))})")
                else:
                    ok = False
                    for ln in hits:
                        lines.append(
                            f"RAW      {rel}:{ln}: lax.psum_scatter outside "
                            f"the grad-sync chokepoint — use apex_tpu."
                            f"parallel.distributed.reduce_scatter_grads "
                            f"(bucketing/telemetry ride on it)")

            if rel.startswith(GRAD_SYNC_PREFIXES):
                psum_hits = _hits(_PSUM, source)
                if psum_hits:
                    ok = False
                    for ln in psum_hits:
                        lines.append(
                            f"RAW      {rel}:{ln}: raw lax.psum in a "
                            f"grad-sync module — route through apex_tpu."
                            f"parallel.distributed (allreduce_grads / "
                            f"grouped_psum) so bucketing policy and ddp/* "
                            f"telemetry cannot be bypassed")
    return ok, lines


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        print("allowed lax.all_gather call sites:")
        for rel in sorted(ALLOWED_GATHER):
            print(f"  {rel}")
        print("allowed lax.psum_scatter call sites:")
        for rel in sorted(ALLOWED_SCATTER):
            print(f"  {rel}")
        print("grad-sync modules (no raw lax.psum/lax.psum_scatter):")
        for rel in GRAD_SYNC_PREFIXES:
            print(f"  {rel}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("raw collective call sites found — route gathers through "
              "apex_tpu/utils/vma.py and grad syncs through "
              "apex_tpu/parallel/distributed.py (or extend the allowlists "
              "in scripts/check_collectives.py with justification)",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
