#!/usr/bin/env python
"""Static check: elastic code never exits the process on its own.

The elastic runtime's exit discipline is a contract: the ONLY way a
training process terminates deliberately is
``AutoResume.request_resume`` (exit 0 inside the preemption grace
window, so the scheduler restarts the job). Any other ``sys.exit`` /
``os._exit`` / builtin ``exit``/``quit`` / ``raise SystemExit`` under
``apex_tpu/elastic/`` would make a failure indistinguishable from a
clean preemption — failures must PROPAGATE as exceptions. This script
AST-walks the elastic package and flags every process-exit spelling; it
also verifies the chokepoint itself still exists (exactly one
``sys.exit``, inside ``AutoResume.request_resume`` in
``apex_tpu/utils/autoresume.py``) so the rule cannot rot silently.

No jax import, pre-commit fast; exits non-zero listing every violation.
Wired into the suite via
``tests/test_observability.py::TestCheckElasticExits``.

Usage::

    python scripts/check_elastic_exits.py          # check, report, 0/1
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ELASTIC_DIR = os.path.join("apex_tpu", "elastic")
CHOKEPOINT_FILE = os.path.join("apex_tpu", "utils", "autoresume.py")
CHOKEPOINT_FUNC = "request_resume"


def _exit_spelling(node) -> str | None:
    """The process-exit spelling of an AST node, or None."""
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if (f.value.id, f.attr) in (("sys", "exit"), ("os", "_exit"),
                                        ("os", "abort")):
                return f"{f.value.id}.{f.attr}"
        if isinstance(f, ast.Name) and f.id in ("exit", "quit"):
            return f.id
    if isinstance(node, ast.Raise) and node.exc is not None:
        exc = node.exc
        name = (exc.func if isinstance(exc, ast.Call) else exc)
        if isinstance(name, ast.Name) and name.id == "SystemExit":
            return "raise SystemExit"
    return None


def _iter_py(root: str):
    for dirpath, _dirnames, filenames in sorted(os.walk(root)):
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def check(repo: str = REPO):
    """Returns ``(ok, report_lines)``."""
    lines, ok = [], True

    pkg = os.path.join(repo, ELASTIC_DIR)
    if not os.path.isdir(pkg):
        return False, [f"MISSING  {ELASTIC_DIR}: elastic package absent"]
    for path in _iter_py(pkg):
        rel = os.path.relpath(path, repo)
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=rel)
            except SyntaxError:
                continue
        clean = True
        for node in ast.walk(tree):
            spelling = _exit_spelling(node)
            if spelling is not None:
                ok = clean = False
                lines.append(
                    f"EXIT     {spelling} ({rel}:{node.lineno}): elastic "
                    f"code must exit only through AutoResume."
                    f"{CHOKEPOINT_FUNC} — raise instead, so failures "
                    f"stay distinguishable from clean preemptions")
        if clean:
            lines.append(f"ok       {rel}")

    # the chokepoint itself: exactly one sys.exit, inside request_resume
    choke = os.path.join(repo, CHOKEPOINT_FILE)
    try:
        with open(choke) as f:
            tree = ast.parse(f.read(), filename=CHOKEPOINT_FILE)
    except OSError:
        return False, lines + [
            f"MISSING  {CHOKEPOINT_FILE}: the AutoResume chokepoint the "
            f"contract is anchored on cannot be read"]
    exits = []
    for func in [n for n in ast.walk(tree)
                 if isinstance(n, ast.FunctionDef)]:
        for node in ast.walk(func):
            if _exit_spelling(node) == "sys.exit":
                exits.append(func.name)
    if exits != [CHOKEPOINT_FUNC]:
        ok = False
        lines.append(
            f"CHOKE    {CHOKEPOINT_FILE}: expected exactly one sys.exit "
            f"inside {CHOKEPOINT_FUNC}, found {exits or 'none'}")
    else:
        lines.append(f"ok       {CHOKEPOINT_FILE}::{CHOKEPOINT_FUNC} is "
                     f"the sole exit chokepoint")
    return ok, lines


def main(argv=None) -> int:
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("elastic exit-discipline violations found — route process "
              "exits through AutoResume.request_resume and raise "
              "exceptions for failures", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
