#!/usr/bin/env python
"""Shim: the elastic exit-discipline contract moved into the unified
static-analysis engine (``apex_tpu.analysis``, rule
``ast-elastic-exits``; chokepoint anchors: ``CHOKEPOINT_FILE``/
``CHOKEPOINT_FUNC`` in ``apex_tpu/analysis/rules_ast.py``, docs:
``docs/ANALYSIS.md``). Historical CLI preserved::

    python scripts/check_elastic_exits.py          # check, report, 0/1
    python -m apex_tpu.analysis --rule ast-elastic-exits   # same rule
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.analysis.astlint import repo_root
from apex_tpu.analysis.core import findings_to_ok_lines
from apex_tpu.analysis.rules_ast import (CHOKEPOINT_FILE,  # noqa: F401
                                         CHOKEPOINT_FUNC, ELASTIC_DIR,
                                         rule_elastic_exits)

REPO = repo_root()


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    return findings_to_ok_lines(*rule_elastic_exits(repo))


def main(argv=None) -> int:
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("elastic exit-discipline violations found — route process "
              "exits through AutoResume.request_resume and raise "
              "exceptions for failures", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
