#!/usr/bin/env python
"""Static check: every bench-config key names a real config field.

The trainer-driven bench legs are built from the declarative
``BENCH_TRAIN_CONFIGS`` table in ``bench.py`` (TrainConfig-shaped nested
dicts), and emitted bench lines may carry a ``config`` block recording
the resolved knobs into ``BENCH_CONFIGS.json``. Both are *data*, so a
renamed dataclass field would not fail at import time — a stale key in a
from-dict path can silently fall a leg back to defaults and the bench
would keep printing numbers for a configuration it no longer runs. This
script AST-walks the config dataclasses (``apex_tpu/config.py``:
TrainConfig/ModelConfig/ParallelConfig/BatchConfig/OptimizerConfig, and
``apex_tpu/models/gpt.py``: GPTConfig — no jax import, pre-commit fast)
and validates:

- every key in ``bench.py``'s ``BENCH_TRAIN_CONFIGS`` legs (top level
  against TrainConfig, nested ``model``/``parallel``/``batch``/
  ``optimizer`` sections against their dataclasses);
- every ``config`` block inside ``BENCH_CONFIGS.json`` entries, same
  rule (the emitted record must stay replayable through
  ``TrainConfig.from_dict``);
- every literal keyword at ``_gpt_train_step(...)`` call sites in
  ``bench.py`` against the function's own parameters plus GPTConfig
  fields (the ``cfg_overrides`` passthrough).

Wired into the test suite via
``tests/test_observability.py::TestCheckBenchConfigs``. Exits non-zero
listing every unknown key.

Usage::

    python scripts/check_bench_configs.py          # check, report, exit 0/1
    python scripts/check_bench_configs.py --list   # print the field tables
"""

from __future__ import annotations

import ast
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG_CLASSES = ("TrainConfig", "ModelConfig", "ParallelConfig",
                  "BatchConfig", "OptimizerConfig")
SECTIONS = {"model": "ModelConfig", "parallel": "ParallelConfig",
            "batch": "BatchConfig", "optimizer": "OptimizerConfig"}


def _dataclass_fields(path: str, class_names) -> dict:
    """``{class_name: {field, ...}}`` from annotated class-body
    assignments (the dataclass field syntax), no import needed."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in class_names:
            fields = set()
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
            out[node.name] = fields
    return out


def field_tables(repo: str = REPO) -> dict:
    tables = _dataclass_fields(
        os.path.join(repo, "apex_tpu", "config.py"), CONFIG_CLASSES)
    tables.update(_dataclass_fields(
        os.path.join(repo, "apex_tpu", "models", "gpt.py"), ("GPTConfig",)))
    missing = [c for c in (*CONFIG_CLASSES, "GPTConfig")
               if not tables.get(c)]
    if missing:
        raise ValueError(f"could not extract fields for {missing}")
    return tables


def _check_spec(spec: dict, tables: dict, where: str, lines: list) -> bool:
    """One TrainConfig-shaped nested dict against the field tables."""
    ok = True
    for key, value in spec.items():
        if key not in tables["TrainConfig"]:
            ok = False
            lines.append(f"UNKNOWN  {where}: {key!r} is not a "
                         f"TrainConfig field")
            continue
        section = SECTIONS.get(key)
        if section and isinstance(value, dict):
            for sub in value:
                if sub not in tables[section]:
                    ok = False
                    lines.append(f"UNKNOWN  {where}: {key}.{sub!r} is "
                                 f"not a {section} field")
    return ok


def _bench_table(bench_path: str):
    """The literal ``BENCH_TRAIN_CONFIGS`` dict from bench.py, or None."""
    with open(bench_path) as f:
        tree = ast.parse(f.read(), filename=bench_path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) \
                        and target.id == "BENCH_TRAIN_CONFIGS":
                    return ast.literal_eval(node.value)
    return None


def _gpt_step_calls(bench_path: str):
    """``(lineno, kw_names)`` of every ``_gpt_train_step(...)`` call,
    plus the def's own parameter names."""
    with open(bench_path) as f:
        tree = ast.parse(f.read(), filename=bench_path)
    own_params = set()
    calls = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_gpt_train_step":
            a = node.args
            own_params = {p.arg for p in
                          (*a.posonlyargs, *a.args, *a.kwonlyargs)}
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else None)
            if name == "_gpt_train_step":
                kws = [k.arg for k in node.keywords if k.arg is not None]
                calls.append((node.lineno, kws))
    return own_params, calls


def check(repo: str = REPO):
    """Returns ``(ok, report_lines)``."""
    lines, ok = [], True
    try:
        tables = field_tables(repo)
    except (OSError, ValueError) as e:
        return False, [f"MISSING  config field tables: {e}"]

    bench_path = os.path.join(repo, "bench.py")
    try:
        table = _bench_table(bench_path)
        own_params, calls = _gpt_step_calls(bench_path)
    except (OSError, SyntaxError, ValueError) as e:
        return False, [f"MISSING  bench.py: {e}"]
    if table is None:
        ok = False
        lines.append("MISSING  bench.py: no literal BENCH_TRAIN_CONFIGS "
                     "table")
    else:
        for leg, spec in table.items():
            where = f"bench.py BENCH_TRAIN_CONFIGS[{leg!r}]"
            if _check_spec(spec, tables, where, lines):
                lines.append(f"ok       {where}: "
                             f"{sum(len(v) if isinstance(v, dict) else 1 for v in spec.values())} keys")
            else:
                ok = False

    allowed = own_params | tables["GPTConfig"]
    for lineno, kws in calls:
        bad = [k for k in kws if k not in allowed]
        if bad:
            ok = False
            lines.append(f"UNKNOWN  bench.py:{lineno} _gpt_train_step "
                         f"keyword(s) {bad} match neither its parameters "
                         f"nor a GPTConfig field")
        else:
            lines.append(f"ok       bench.py:{lineno} _gpt_train_step call")

    results_path = os.path.join(repo, "BENCH_CONFIGS.json")
    if os.path.exists(results_path):
        try:
            with open(results_path) as f:
                entries = json.load(f)
        except (OSError, ValueError) as e:
            return False, lines + [f"MISSING  BENCH_CONFIGS.json: {e}"]
        for entry in entries if isinstance(entries, list) else []:
            cfg = entry.get("config") if isinstance(entry, dict) else None
            if isinstance(cfg, dict):
                where = (f"BENCH_CONFIGS.json "
                         f"[{entry.get('metric', '?')}].config")
                if not _check_spec(cfg, tables, where, lines):
                    ok = False
                else:
                    lines.append(f"ok       {where}")
    return ok, lines


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        for cls, fields in sorted(field_tables().items()):
            print(f"{cls}: {', '.join(sorted(fields))}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("unknown bench-config keys found — a renamed config field "
              "must be renamed in bench.py's BENCH_TRAIN_CONFIGS / "
              "emitted config blocks too, or the leg silently falls "
              "back to defaults", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
