#!/usr/bin/env python
"""Shim: the bench-config field contract moved into the unified
static-analysis engine (``apex_tpu.analysis``, rule
``ast-bench-configs``; field tables come from the config dataclasses via
``bench_field_tables`` in ``apex_tpu/analysis/rules_ast.py``, docs:
``docs/ANALYSIS.md``). Historical CLI preserved::

    python scripts/check_bench_configs.py          # check, report, exit 0/1
    python scripts/check_bench_configs.py --list   # print the field tables
    python -m apex_tpu.analysis --rule ast-bench-configs   # same rule
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.analysis.astlint import repo_root
from apex_tpu.analysis.core import findings_to_ok_lines
from apex_tpu.analysis.rules_ast import (CONFIG_CLASSES, SECTIONS,  # noqa: F401
                                         bench_field_tables as field_tables,
                                         rule_bench_configs)

REPO = repo_root()


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    return findings_to_ok_lines(*rule_bench_configs(repo))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        for cls, fields in sorted(field_tables(REPO).items()):
            print(f"{cls}: {', '.join(sorted(fields))}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("unknown bench-config keys found — a renamed config field "
              "must be renamed in bench.py's BENCH_TRAIN_CONFIGS / "
              "emitted config blocks too, or the leg silently falls "
              "back to defaults", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
