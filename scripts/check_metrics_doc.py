#!/usr/bin/env python
"""Static check: every in-graph metric recorded in source is documented.

The per-step metric families (``health/*``, ``tp/*``, ``amp/*``,
``ddp/*``, ``pipeline/*``, ``optim/*``, ``zero/*``, ``mem/*``,
``perf/*``) are a public contract — dashboards
and the crash-dump post-mortem workflow key on the names — and the
contract lives in the docs/OBSERVABILITY.md table. A ``record()`` call
added without a doc row silently grows an undocumented surface; this
script AST-walks the package for ``record(...)`` call sites — and
``gauge(...)`` call sites, the host-registry half the ``mem/*`` family
lives on — extracts the
metric-name first argument (plain string literals, and f-strings whose
formatted fields normalize to a ``<>`` placeholder — ``f"health/{name}/l2"``
checks as ``health/<>/l2``), and requires each name in a checked family to
appear in backticks somewhere in the doc (doc placeholders like
``<tree>`` normalize the same way). No jax import, pre-commit fast; exits
non-zero listing every undocumented name. Wired into the test suite via
``tests/test_observability.py::TestCheckMetricsDoc``.

Usage::

    python scripts/check_metrics_doc.py          # check, report, exit 0/1
    python scripts/check_metrics_doc.py --list   # print recorded names
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "apex_tpu"
DOC = os.path.join("docs", "OBSERVABILITY.md")

# metric families under the documentation contract; names outside these
# prefixes (host registry internals, ad-hoc example metrics) are exempt
PREFIXES = ("health/", "tp/", "amp/", "ddp/", "pipeline/", "optim/",
            "zero/", "mem/", "perf/", "ckpt/", "resume/", "serve/")

# callees whose literal first argument is a metric name: in-graph
# ``ingraph.record(...)`` and the host-registry accessors — ``gauge``
# (the mem/* family is static per compile, so it rides gauges, not
# records) plus ``counter``/``histogram``, which the elastic runtime's
# ckpt/* and resume/* families ride
CALLEES = ("record", "gauge", "counter", "histogram")

_PLACEHOLDER = re.compile(r"<[^<>`]*>")


def _norm(name: str) -> str:
    """Collapse every ``<...>`` placeholder spelling to ``<>`` so the
    source's ``f"health/{name}/l2"`` matches the doc's
    ``health/<tree>/l2``."""
    return _PLACEHOLDER.sub("<>", name)


def _literal_name(node) -> str | None:
    """The metric-name string of a ``record()`` first argument, with
    f-string fields as ``<>`` — None when it is not statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:  # FormattedValue
                parts.append("<>")
        return "".join(parts)
    return None


def recorded_names(repo: str = REPO):
    """Yield ``(relpath, lineno, name)`` for every ``record(...)`` /
    ``gauge(...)`` metric name in the package that falls under a checked
    prefix."""
    pkg_root = os.path.join(repo, PACKAGE)
    for dirpath, _dirnames, filenames in sorted(os.walk(pkg_root)):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=rel)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                func = node.func
                callee = (func.id if isinstance(func, ast.Name)
                          else func.attr if isinstance(func, ast.Attribute)
                          else None)
                if callee not in CALLEES:
                    continue
                name = _literal_name(node.args[0])
                if name is not None and _norm(name).startswith(PREFIXES):
                    yield rel, node.lineno, name


def documented_names(repo: str = REPO) -> set:
    """Every backticked token in the observability doc, normalized."""
    with open(os.path.join(repo, DOC)) as f:
        text = f.read()
    return {_norm(tok) for tok in re.findall(r"`([^`\n]+)`", text)}


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    try:
        documented = documented_names(repo)
    except OSError:
        return False, [f"MISSING  {DOC}: cannot read the metric table"]
    lines, ok = [], True
    for rel, lineno, name in recorded_names(repo):
        if _norm(name) in documented:
            lines.append(f"ok       {name} ({rel}:{lineno})")
        else:
            ok = False
            lines.append(f"UNDOC    {name} ({rel}:{lineno}): recorded but "
                         f"absent from {DOC}")
    return ok, lines


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        for rel, lineno, name in recorded_names():
            print(f"{name}\t{rel}:{lineno}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("undocumented metrics found — add rows to the "
              "docs/OBSERVABILITY.md table (placeholders like <tree> "
              "match f-string fields) or rename outside the checked "
              "families in scripts/check_metrics_doc.py", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
