#!/usr/bin/env python
"""Shim: the metric-name documentation contract moved into the unified
static-analysis engine (``apex_tpu.analysis``, rules ``ast-metrics-doc``
+ the ``ast-metric-families`` meta-lint; family list:
``METRIC_PREFIXES`` in ``apex_tpu/analysis/rules_ast.py``, docs:
``docs/ANALYSIS.md``). Running this shim checks BOTH: per-name doc rows
for the checked families, and — new — that no call site opens a metric
family outside the registered list at all (the list used to be grown by
hand per PR). Historical CLI preserved::

    python scripts/check_metrics_doc.py          # check, report, exit 0/1
    python scripts/check_metrics_doc.py --list   # print recorded names
    python -m apex_tpu.analysis --rule ast-metrics-doc
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.analysis.astlint import repo_root
from apex_tpu.analysis.core import findings_to_ok_lines
from apex_tpu.analysis.rules_ast import (METRIC_CALLEES as CALLEES,  # noqa: F401
                                         METRIC_PREFIXES as PREFIXES,
                                         _metric_names,
                                         rule_metric_families,
                                         rule_metrics_doc)

REPO = repo_root()


def check(repo: str = REPO):
    """Returns (ok, report_lines) — the doc-row check plus the
    family meta-lint."""
    doc_f, doc_n = rule_metrics_doc(repo)
    fam_f, fam_n = rule_metric_families(repo)
    return findings_to_ok_lines(doc_f + fam_f, doc_n + fam_n)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        for rel, lineno, name in _metric_names(REPO):
            print(f"{name}\t{rel}:{lineno}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("undocumented metrics (or an unregistered metric family) "
              "found — add rows to the docs/OBSERVABILITY.md table "
              "(placeholders like <tree> match f-string fields) and "
              "register new families in METRIC_PREFIXES "
              "(apex_tpu/analysis/rules_ast.py)", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
