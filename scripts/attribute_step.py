#!/usr/bin/env python
"""Per-region step-time attribution of the bench workloads (pyprof).

Generalizes the round-5 ``scripts/profile_rn50.py`` ablation ladder: one
entry point builds the bench-identical train step for ``--model gpt`` or
``--model rn50``, AOT-compiles it, measures the wall step time, prices
every ``named_scope`` region against the chip's roofline
(``apex_tpu.pyprof``), and prints the attribution as a markdown table
(plus JSONL with ``--json``). This is the instrument the "win the
flagship benches" work reads its next move from: the gap between
``measured_step_ms`` and ``modeled_step_ms``, region by region, with
``comm_exposed_ms`` isolating collectives the schedule failed to hide.

Validation: by default the GPT step is built with the layer scan fully
unrolled and the XLA attention path (``use_flash=False``) so XLA's
``cost_analysis`` can count the whole program, and the run FAILS if the
model's total FLOPs disagree with ``costs.flops_budget(compiled)`` by
more than ``--tolerance`` (5%) — the model stays honest against the
compiler. ``--flash`` attributes the real Mosaic-kernel program instead
(Mosaic custom calls report zero cost to XLA, so validation is skipped
and the analytic model is the only source). RN50 has no scanned stacks,
so it validates as-is.

Usage::

    python scripts/attribute_step.py --model gpt
    python scripts/attribute_step.py --model gpt --config '{"hidden_size": 256, "num_layers": 4}'
    python scripts/attribute_step.py --model rn50 --json
    python scripts/attribute_step.py --model gpt --trace-dir /tmp/prof  # measured per-region walls
"""

import argparse
import json
import os
import sys

import numpy as np

# runnable as `python scripts/attribute_step.py` from a checkout: the
# repo root (where apex_tpu/ lives) is the script dir's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _timeit(fn, args, iters, warmup):
    """Mean per-iteration seconds via ``bench._timeit`` — the SAME
    chunked, fetch-RTT-subtracted methodology every bench line uses, so
    ``measured_step_ms`` here is directly comparable to the bench
    ``step_ms`` the attribution budget is read against (a per-iteration
    sync would time the host->device tunnel, not the chip)."""
    import bench
    times = bench._timeit(fn, args, max(1, iters), max(1, warmup),
                          chunk=max(1, min(iters, 10)))
    return float(np.mean(times))


def build_gpt(config: dict, flash: bool):
    """The bench config-5 GPT-small train step, built by
    ``bench._gpt_train_step`` itself — the SAME constructor
    :func:`bench.bench_gpt` and the remat sweep use, so the attribution
    instrument cannot drift from the benched program. ``config``
    overrides GPTConfig fields plus ``batch``/``seq``. Returns
    (traced, compiled, args, wrapped).

    Default = VALIDATION mode: XLA attention, fully unrolled layer scan,
    fp32 compute laid over the bench defaults — the configuration XLA's
    cost_analysis can count end to end (a while body is priced once
    regardless of trip count, Mosaic custom calls report zero cost, and
    the CPU backend inflates bf16 transcendental expansions into counted
    flops), so the roofline model is checked against the compiler every
    run. ``--bench`` keeps the bench defaults untouched (bf16 + Mosaic
    flash + scanned stack) with validation off. Per-region FLOP counts
    and shares are dtype-independent; HBM bytes in validation mode price
    the fp32 activation footprint."""
    import jax.numpy as jnp

    import bench

    config = dict(config)
    kw = dict(batch=config.pop("batch", 8), seq=config.pop("seq", 1024))
    # GPTConfig field -> _gpt_train_step parameter renames; every other
    # config key passes through as a cfg_override laid over the bench
    # defaults
    for field, param in (("hidden_size", "hidden"),
                         ("num_layers", "layers"),
                         ("num_attention_heads", "heads"),
                         ("vocab_size", "vocab")):
        if field in config:
            kw[param] = config.pop(field)
    overrides = {} if flash else dict(compute_dtype=jnp.float32,
                                      use_flash=False,
                                      layer_scan_unroll=True)
    overrides.update(config)
    _cfg, args, wrapped, compiled, traced = bench._gpt_train_step(
        **kw, **overrides)
    return traced, compiled, args, wrapped


def build_rn50(config: dict, flash: bool):
    """The bench headline RN50 train step (amp O2, FusedSGD momentum,
    donated buffers); ``config`` overrides ``batch``/``img``/ResNetConfig
    fields. Default = validation mode: fp32 compute and per-leaf FusedSGD
    — same math as the headline, but countable by XLA (the CPU backend
    books the FlatOptimizer's shared flat-buffer computation once per
    leaf slice, inflating its flop count ~100x, and bf16 transcendental
    expansions as flops); ``--bench`` restores the bench-identical
    bf16 + FlatOptimizer program with validation off. Returns (traced,
    compiled, args, wrapped)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp.scaler import DynamicLossScale, all_finite
    from apex_tpu.models import ResNet50, ResNetConfig
    from apex_tpu.optimizers import FlatOptimizer, FusedSGD

    config = dict(config)
    batch = config.pop("batch", 256)
    img = config.pop("img", 224)
    kw = dict(num_classes=1000,
              compute_dtype=jnp.bfloat16 if flash else jnp.float32)
    kw.update(config)
    cfg = ResNetConfig(**kw)
    model = ResNet50(cfg)
    params, bn_state = model.init(jax.random.PRNGKey(0))
    sgd = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    opt = FlatOptimizer(sgd) if flash else sgd
    opt_state = opt.init(params)
    scaler = DynamicLossScale(init_scale=2.0 ** 12)
    ls = scaler.init()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, img, img, 3), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, kw["num_classes"], batch))

    def loss_fn(params, bn_state, scale):
        logits, new_bn = model(params, bn_state, x, training=True)
        onehot = jax.nn.one_hot(labels, kw["num_classes"])
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
        return loss * scale, (loss, new_bn)

    @(lambda f: jax.jit(f, donate_argnums=(0, 1, 2, 3)))
    def step(params, bn_state, opt_state, ls):
        grads, (loss, new_bn) = jax.grad(loss_fn, has_aux=True)(
            params, bn_state, ls.loss_scale)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite,
                                     scale=1.0 / ls.loss_scale)
        return params, new_bn, opt_state, new_ls

    traced = step.trace(params, bn_state, opt_state, ls)
    compiled = traced.lower().compile()

    def wrapped(params, bn_state, opt_state, ls):
        # outputs match the input order exactly, so the _timeit
        # state-threading convention holds without reshuffling
        return compiled(params, bn_state, opt_state, ls)

    return traced, compiled, (params, bn_state, opt_state, ls), wrapped


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--model", choices=("gpt", "rn50"), default="gpt")
    parser.add_argument("--config", default="{}",
                        help="JSON overrides: model fields plus batch/seq "
                             "(gpt) or batch/img (rn50)")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--bench", "--flash", dest="bench",
                        action="store_true",
                        help="attribute the bench-identical program "
                             "(gpt: bf16 + Mosaic flash + scanned stack; "
                             "rn50: bf16 + FlatOptimizer) instead of the "
                             "XLA-countable validation twin; skips "
                             "validation")
    parser.add_argument("--json", action="store_true",
                        help="also print the JSONL form")
    parser.add_argument("--trace-dir", default=None,
                        help="jax.profiler trace dir for measured "
                             "per-region walls")
    parser.add_argument("--trace-steps", type=int, default=1,
                        help="number of steps the --trace-dir capture "
                             "spans (durations divide by it so walls "
                             "are per-step)")
    parser.add_argument("--tolerance", type=float, default=0.05,
                        help="max |modeled/xla - 1| before failing")
    parser.add_argument("--no-validate", action="store_true")
    args = parser.parse_args(argv)
    config = json.loads(args.config)

    build = build_gpt if args.model == "gpt" else build_rn50
    traced, compiled, step_args, wrapped = build(config, args.bench)
    step_time_s = _timeit(wrapped, step_args, args.iters, args.warmup)

    from apex_tpu.pyprof import attribute
    report = attribute(traced, step_time_s, compiled=compiled,
                       trace_dir=args.trace_dir,
                       trace_steps=args.trace_steps)
    print(f"# {args.model} step-time attribution "
          f"({report.spec.name}, {args.iters} iters)")
    print(report.markdown())
    if args.json:
        print(report.json_lines())

    # --bench programs are exactly what XLA cannot count honestly (gpt:
    # Mosaic flash + scanned stack; rn50: FlatOptimizer call inflation)
    validate = not (args.no_validate or args.bench)
    if validate:
        if not report.xla_flops:
            print("validation skipped: backend reports no cost analysis",
                  file=sys.stderr)
            return 0
        delta = report.flops / report.xla_flops - 1.0
        verdict = "ok" if abs(delta) <= args.tolerance else "FAIL"
        print(f"validation {verdict}: modeled flops within {delta:+.2%} "
              f"of costs.flops_budget(compiled) "
              f"(tolerance {args.tolerance:.0%})")
        if verdict == "FAIL":
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
