#!/usr/bin/env python
"""Static check: every ``checkpoint_name`` tag literal comes from the
central registry.

The activation-remat policies (``apex_tpu/remat.py``) address activations
by name: ``save_only_these_names`` / ``save_and_offload_only_these_names``
save exactly the tags the models emit. A tag literal outside
``remat.CHECKPOINT_NAMES`` is an orphan — no policy can reach it, and a
save-list naming it would pass ``RematPolicy`` validation against a
registry that doesn't know the activation exists. ``remat.tag`` validates
at trace time; this script catches the same class *statically* (including
raw ``jax.ad_checkpoint.checkpoint_name`` calls that bypass the
chokepoint), no jax import, pre-commit fast.

It AST-walks the package for calls whose callee is ``checkpoint_name``,
``tag`` or a ``_tag`` method (the models' policy-gated tagger) with a
string-literal name in the second argument, parses the registry tuple out
of ``apex_tpu/remat.py`` (also statically), and exits non-zero listing
every literal not in the registry — plus any ``SELECTIVE_SAVE`` entry
missing from ``CHECKPOINT_NAMES`` (the save-list must be a registry
subset). Wired into the test suite via
``tests/test_observability.py::TestCheckRematNames``.

Usage::

    python scripts/check_remat_names.py          # check, report, exit 0/1
    python scripts/check_remat_names.py --list   # print tag sites + registry
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "apex_tpu"
REGISTRY_FILE = os.path.join(PACKAGE, "remat.py")

# callee spellings that denote a checkpoint-name tag. ``_tag`` is the
# models' policy-gated bound tagger (identity under none/full); ``tag``
# the remat-module chokepoint; ``checkpoint_name`` the raw jax call.
TAG_CALLEES = ("checkpoint_name", "tag", "_tag", "_remat_tag")


def _tuple_literal(node) -> list:
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def registry(repo: str = REPO):
    """``(CHECKPOINT_NAMES, SELECTIVE_SAVE)`` parsed from the registry
    module's AST — raises OSError/ValueError when the module or the
    assignments are missing (a moved registry must move this scan too)."""
    with open(os.path.join(repo, REGISTRY_FILE)) as f:
        tree = ast.parse(f.read(), filename=REGISTRY_FILE)
    names = save = None
    for node in ast.walk(tree):
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = (node.target,)
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "CHECKPOINT_NAMES":
                names = _tuple_literal(node.value)
            if isinstance(t, ast.Name) and t.id == "SELECTIVE_SAVE":
                save = _tuple_literal(node.value)
    if not names:
        raise ValueError(
            f"{REGISTRY_FILE} defines no CHECKPOINT_NAMES tuple literal")
    return tuple(names), tuple(save or ())


def tag_sites(repo: str = REPO):
    """Yield ``(relpath, lineno, name)`` for every statically-known tag
    literal in the package (registry module excluded — its docstrings and
    error messages mention names by design)."""
    pkg_root = os.path.join(repo, PACKAGE)
    for dirpath, _dirnames, filenames in sorted(os.walk(pkg_root)):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo)
            if rel == REGISTRY_FILE:
                continue
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=rel)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                callee = (func.id if isinstance(func, ast.Name)
                          else func.attr if isinstance(func, ast.Attribute)
                          else None)
                if callee not in TAG_CALLEES:
                    continue
                # the name rides as the positional second argument or as
                # the name= keyword (raw checkpoint_name accepts both)
                name = node.args[1] if len(node.args) >= 2 else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "name"), None)
                if isinstance(name, ast.Constant) and isinstance(
                        name.value, str):
                    yield rel, node.lineno, name.value


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    try:
        names, save = registry(repo)
    except (OSError, ValueError) as e:
        return False, [f"MISSING  registry: {e}"]
    lines, ok = [], True
    for extra in [n for n in save if n not in names]:
        ok = False
        lines.append(f"ORPHAN   SELECTIVE_SAVE entry {extra!r} is not in "
                     f"CHECKPOINT_NAMES")
    for rel, lineno, name in tag_sites(repo):
        if name in names:
            lines.append(f"ok       {name} ({rel}:{lineno})")
        else:
            ok = False
            lines.append(f"ORPHAN   {name} ({rel}:{lineno}): tagged but "
                         f"absent from remat.CHECKPOINT_NAMES — no policy "
                         f"can save it")
    return ok, lines


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        names, save = registry()
        print("CHECKPOINT_NAMES:", ", ".join(names))
        print("SELECTIVE_SAVE:  ", ", ".join(save))
        for rel, lineno, name in tag_sites():
            print(f"{name}\t{rel}:{lineno}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("orphan checkpoint names found — register them in "
              "apex_tpu/remat.py CHECKPOINT_NAMES (and SELECTIVE_SAVE if "
              "they should stay resident under the selective policy)",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
