#!/usr/bin/env python
"""Shim: the checkpoint-name registry contract moved into the unified
static-analysis engine (``apex_tpu.analysis``, rule ``ast-remat-names``;
tag spellings: ``TAG_CALLEES`` in ``apex_tpu/analysis/rules_ast.py``,
docs: ``docs/ANALYSIS.md``). Historical CLI preserved::

    python scripts/check_remat_names.py          # check, report, exit 0/1
    python scripts/check_remat_names.py --list   # print tag sites + registry
    python -m apex_tpu.analysis --rule ast-remat-names   # same rule
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.analysis.astlint import repo_root
from apex_tpu.analysis.core import findings_to_ok_lines
from apex_tpu.analysis.rules_ast import (REGISTRY_FILE, TAG_CALLEES,  # noqa: F401
                                         _remat_registry as registry,
                                         _tag_sites as tag_sites,
                                         rule_remat_names)

REPO = repo_root()


def check(repo: str = REPO):
    """Returns (ok, report_lines)."""
    return findings_to_ok_lines(*rule_remat_names(repo))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--list" in argv:
        names, save = registry(REPO)
        print("CHECKPOINT_NAMES:", ", ".join(names))
        print("SELECTIVE_SAVE:  ", ", ".join(save))
        for rel, lineno, name in tag_sites(REPO):
            print(f"{name}\t{rel}:{lineno}")
        return 0
    ok, lines = check()
    for line in lines:
        print(line)
    if not ok:
        print("orphan checkpoint names found — register them in "
              "apex_tpu/remat.py CHECKPOINT_NAMES (and SELECTIVE_SAVE if "
              "they should stay resident under the selective policy)",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
